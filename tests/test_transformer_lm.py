"""Causal LM mode of the transformer family: per-token next-token loss,
causal masking end-to-end, every trainer unchanged."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from split_learning_tpu.data.datasets import synthetic_lm
from split_learning_tpu.models import get_plan
from split_learning_tpu.models.transformer import transformer_plan
from split_learning_tpu.parallel.mesh import make_mesh
from split_learning_tpu.runtime.fused import FusedSplitTrainer
from split_learning_tpu.utils import Config

B, T, V = 8, 32, 256


@pytest.fixture(scope="module")
def lm_data():
    ds = synthetic_lm(seq_len=T)
    return ds


def test_dataset_labels_are_shifted_inputs(lm_data):
    x, y = lm_data.train.x, lm_data.train.y
    assert x.shape == y.shape and x.dtype == np.int32
    # y[t] is the chain's next token: y[:, :-1] == x[:, 1:]
    np.testing.assert_array_equal(y[:, :-1], x[:, 1:].astype(np.int64))


def test_lm_head_shapes_and_causality():
    """Per-token logits; token t's logits must not depend on tokens > t
    (causal masking through every block)."""
    plan = get_plan(model="transformer_lm", mode="split")
    rs = np.random.RandomState(0)
    x = rs.randint(0, V, (2, T)).astype(np.int32)
    params = plan.init(jax.random.PRNGKey(0), x)
    logits = np.asarray(plan.apply(params, x))
    assert logits.shape == (2, T, V)
    # perturb the future: logits at position t0 must be unchanged
    t0 = 10
    x2 = x.copy()
    x2[:, t0 + 1:] = (x2[:, t0 + 1:] + 7) % V
    logits2 = np.asarray(plan.apply(params, x2))
    np.testing.assert_allclose(logits[:, :t0 + 1], logits2[:, :t0 + 1],
                               atol=1e-5)
    assert np.abs(logits[:, t0 + 1:] - logits2[:, t0 + 1:]).max() > 1e-3


@pytest.mark.slow
def test_lm_trains_below_unigram_entropy(lm_data):
    """The model must learn to USE context: its next-token loss must end
    below the empirical unigram cross-entropy — the best any
    context-free predictor can do on this chain."""
    counts = np.bincount(lm_data.train.y.ravel(), minlength=V)
    p = counts / counts.sum()
    unigram_ce = -np.sum(p[p > 0] * np.log(p[p > 0]))

    cfg = Config(mode="split", model="transformer_lm", batch_size=64,
                 lr=0.1, momentum=0.9)
    tr = FusedSplitTrainer(get_plan(model="transformer_lm"), cfg,
                           jax.random.PRNGKey(0), lm_data.train.x[:64])
    losses = []
    for i in range(60):
        lo = 64 * i % 4032
        losses.append(tr.train_step(lm_data.train.x[lo:lo + 64],
                                    lm_data.train.y[lo:lo + 64]))
    assert losses[0] > unigram_ce  # starts ~log(256), above unigram
    assert min(losses[-5:]) < unigram_ce - 0.2


@pytest.mark.slow
def test_lm_ring_seq_parallel_matches_dense(devices, lm_data):
    """Causal ring attention under (2 data x 4 seq) reproduces the
    single-device LM loss series — the long-context training config."""
    cfg = Config(mode="split", model="transformer_lm", batch_size=B)
    dense = FusedSplitTrainer(transformer_plan(lm=True), cfg,
                              jax.random.PRNGKey(0), lm_data.train.x[:B])
    mesh = make_mesh(num_clients=2, num_stages=1, seq_parallel=4,
                     devices=devices)
    ring = FusedSplitTrainer(
        transformer_plan(lm=True, mesh=mesh, attn="ring"), cfg,
        jax.random.PRNGKey(0), lm_data.train.x[:B], mesh=mesh)
    for i in range(2):
        xb = lm_data.train.x[B * i:B * (i + 1)]
        yb = lm_data.train.y[B * i:B * (i + 1)]
        np.testing.assert_allclose(ring.train_step(xb, yb),
                                   dense.train_step(xb, yb),
                                   atol=5e-5, rtol=5e-5)


@pytest.mark.slow
def test_lm_u_split_pipeline_matches_fused(devices, lm_data):
    """The GPipe pipeline carries per-token [T, V] logits in its logits
    slot (generalized from the classifier's [C])."""
    from split_learning_tpu.parallel.pipeline import PipelinedTrainer

    cfg = Config(mode="u_split", model="transformer_lm", batch_size=8,
                 microbatches=2)
    plan = transformer_plan(mode="u_split", lm=True)
    mesh = make_mesh(num_clients=2, num_stages=3, devices=devices)
    x, y = lm_data.train.x[:8], lm_data.train.y[:8]
    piped = PipelinedTrainer(plan, cfg, jax.random.PRNGKey(0), x, mesh)
    fused = FusedSplitTrainer(plan, cfg, jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(piped.train_step(x, y),
                               fused.train_step(x, y),
                               atol=5e-5, rtol=5e-5)


@pytest.mark.slow
def test_lm_cli_end_to_end(tmp_path, capsys):
    from split_learning_tpu.launch.run import main
    rc = main(["train", "--mode", "split", "--transport", "fused",
               "--model", "transformer_lm", "--dataset", "lm",
               "--steps", "3", "--batch-size", "8", "--epochs", "1",
               "--data-dir", str(tmp_path), "--tracking", "noop",
               "--eval"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "[done]" in out and "accuracy" in out


@pytest.mark.slow
def test_greedy_generate_self_consistent(lm_data):
    """Greedy decode invariants: the prompt is preserved verbatim, and
    re-running the forward on the finished sequence reproduces every
    generated token (teacher-forcing self-consistency)."""
    from split_learning_tpu.runtime.generate import greedy_generate

    plan = get_plan(model="transformer_lm")
    prompt = lm_data.train.x[:4, :8]
    params = plan.init(jax.random.PRNGKey(1), prompt)
    n_new = 6
    out = np.asarray(greedy_generate(plan, params, prompt, n_new))
    assert out.shape == (4, 8 + n_new)
    np.testing.assert_array_equal(out[:, :8], prompt)
    logits = np.asarray(plan.apply(list(params), jnp.asarray(out)))
    for i in range(n_new):
        pos = 8 + i
        np.testing.assert_array_equal(
            np.argmax(logits[:, pos - 1], axis=-1), out[:, pos])


def test_kv_cache_decode_matches_reforward_tiny(lm_data):
    """Core-tier KV sanity: tiny model, greedy only, one plan shape —
    the full cross-mode/sampled matrix lives in the slow tier below."""
    from split_learning_tpu.runtime.generate import greedy_generate

    plan = transformer_plan(lm=True, vocab=V, d_model=16, num_heads=1,
                            client_depth=1, server_depth=1, max_len=64)
    prompt = lm_data.train.x[:2, :5]
    params = plan.init(jax.random.PRNGKey(4), prompt)
    ref = np.asarray(greedy_generate(plan, params, prompt, 4,
                                     kv_cache=False))
    kv = np.asarray(greedy_generate(plan, params, prompt, 4,
                                    kv_cache=True))
    np.testing.assert_array_equal(kv, ref)


@pytest.mark.slow
def test_kv_cache_decode_matches_reforward(lm_data):
    """The KV-cache decode program (prefill + per-token cached steps) is
    token-exact against the O(T^2) re-forward reference path, greedy and
    sampled, on both plan shapes."""
    from split_learning_tpu.runtime.generate import (greedy_generate,
                                                     sample_generate)

    prompt = lm_data.train.x[:3, :9]
    for mode in ("split", "u_split"):
        plan = transformer_plan(mode=mode, lm=True)
        params = plan.init(jax.random.PRNGKey(2), prompt)
        ref = np.asarray(greedy_generate(plan, params, prompt, 7,
                                         kv_cache=False))
        kv = np.asarray(greedy_generate(plan, params, prompt, 7,
                                        kv_cache=True))
        np.testing.assert_array_equal(kv, ref)
        rs = np.asarray(sample_generate(plan, params, prompt, 7,
                                        jax.random.PRNGKey(5), 0.7,
                                        kv_cache=False))
        ks = np.asarray(sample_generate(plan, params, prompt, 7,
                                        jax.random.PRNGKey(5), 0.7,
                                        kv_cache=True))
        np.testing.assert_array_equal(ks, rs)
        # n_new=1: the scan body runs zero times
        one = np.asarray(greedy_generate(plan, params, prompt, 1))
        np.testing.assert_array_equal(one[:, :-1], prompt)
        np.testing.assert_array_equal(one, ref[:, :prompt.shape[1] + 1])


@pytest.mark.slow
def test_greedy_generate_learns_chain_transitions(lm_data):
    """After training, generation follows the chain: a decent fraction
    of generated tokens are the true modal successor of their
    predecessor (far above the 1/V chance rate)."""
    from split_learning_tpu.runtime.generate import greedy_generate

    cfg = Config(mode="split", model="transformer_lm", batch_size=64,
                 lr=0.1, momentum=0.9)
    tr = FusedSplitTrainer(get_plan(model="transformer_lm"), cfg,
                           jax.random.PRNGKey(0), lm_data.train.x[:64])
    for i in range(40):
        lo = 64 * i % 4032
        tr.train_step(lm_data.train.x[lo:lo + 64],
                      lm_data.train.y[lo:lo + 64])

    # recover the chain's modal successor map from the training data
    nxt = np.zeros((V, V), np.int64)
    xs, ys = lm_data.train.x, lm_data.train.y
    np.add.at(nxt, (xs.ravel(), ys.ravel()), 1)
    modal = nxt.argmax(axis=1)

    out = np.asarray(greedy_generate(
        tr.plan, tr.params, lm_data.train.x[:8, :8], 16))
    gen_prev = out[:, 7:-1].ravel()
    gen_next = out[:, 8:].ravel()
    hit = float(np.mean(gen_next == modal[gen_prev]))
    assert hit > 0.25, f"modal-successor hit rate {hit} barely above chance"


@pytest.mark.slow
def test_sample_generate_determinism_and_range(lm_data):
    """Sampling decode: deterministic under a fixed key, different keys
    diverge, tokens stay in-vocab, and a near-zero temperature recovers
    the greedy path."""
    from split_learning_tpu.runtime.generate import (
        greedy_generate, sample_generate)

    plan = get_plan(model="transformer_lm")
    prompt = lm_data.train.x[:4, :8]
    params = plan.init(jax.random.PRNGKey(2), prompt)
    k1, k2 = jax.random.PRNGKey(10), jax.random.PRNGKey(11)
    a = np.asarray(sample_generate(plan, params, prompt, 8, k1))
    b = np.asarray(sample_generate(plan, params, prompt, 8, k1))
    c = np.asarray(sample_generate(plan, params, prompt, 8, k2))
    np.testing.assert_array_equal(a, b)
    assert (a != c).any()
    assert a.min() >= 0 and a.max() < V
    cold = np.asarray(sample_generate(plan, params, prompt, 8, k1,
                                      temperature=1e-4))
    greedy = np.asarray(greedy_generate(plan, params, prompt, 8))
    np.testing.assert_array_equal(cold, greedy)


def test_sample_generate_rejects_nonpositive_temperature(lm_data):
    from split_learning_tpu.runtime.generate import sample_generate
    plan = get_plan(model="transformer_lm")
    prompt = lm_data.train.x[:2, :8]
    params = plan.init(jax.random.PRNGKey(2), prompt)
    with pytest.raises(ValueError, match="temperature"):
        sample_generate(plan, params, prompt, 4, jax.random.PRNGKey(0),
                        temperature=0.0)


@pytest.mark.slow
def test_topk_topp_sampling(lm_data):
    """top-k / nucleus filtering invariants: top_k=1 and top_p→0 both
    collapse to greedy at any temperature; top_k=k samples stay inside
    the top-k set of the realized sequence's own logits; kv and
    re-forward paths agree token-exactly under the same filters."""
    from split_learning_tpu.runtime.generate import (greedy_generate,
                                                     sample_generate)

    plan = transformer_plan(lm=True, vocab=V, d_model=16, num_heads=1,
                            client_depth=1, server_depth=1, max_len=64)
    prompt = lm_data.train.x[:2, :6]
    params = plan.init(jax.random.PRNGKey(6), prompt)
    greedy = np.asarray(greedy_generate(plan, params, prompt, 5))

    # top_k=1: sampling cannot deviate from argmax, whatever the rng/T
    k1 = np.asarray(sample_generate(plan, params, prompt, 5,
                                    jax.random.PRNGKey(9), 3.0, top_k=1))
    np.testing.assert_array_equal(k1, greedy)
    # top_p -> 0: only the single most-probable token survives
    p0 = np.asarray(sample_generate(plan, params, prompt, 5,
                                    jax.random.PRNGKey(9), 3.0,
                                    top_p=1e-6))
    np.testing.assert_array_equal(p0, greedy)

    # top_k=3 at hot temperature: every generated token is in the top-3
    # of the logits that produced it (teacher-forcing check)
    out = np.asarray(sample_generate(plan, params, prompt, 5,
                                     jax.random.PRNGKey(7), 2.0,
                                     top_k=3))
    logits = np.asarray(plan.apply(list(params), jnp.asarray(out)))
    for pos in range(6, 11):
        top3 = np.argsort(-logits[:, pos - 1], axis=-1)[:, :3]
        for row in range(out.shape[0]):
            assert out[row, pos] in top3[row], (pos, row)

    # kv and re-forward paths agree under identical filters
    a = np.asarray(sample_generate(plan, params, prompt, 5,
                                   jax.random.PRNGKey(8), 0.9,
                                   top_k=4, top_p=0.8, kv_cache=True))
    b = np.asarray(sample_generate(plan, params, prompt, 5,
                                   jax.random.PRNGKey(8), 0.9,
                                   top_k=4, top_p=0.8, kv_cache=False))
    np.testing.assert_array_equal(a, b)

    # argument validation
    for bad in ({"top_k": -1}, {"top_p": 0.0}, {"top_p": 1.5}):
        with pytest.raises(ValueError):
            sample_generate(plan, params, prompt, 2,
                            jax.random.PRNGKey(0), **bad)
