"""slt-lint (PR 6): the static rules on per-rule fixtures (positive,
negative, waiver), the SLT002 CFG on try/finally and early-return
shapes, the engine's exit-code contract, the spans-registry drift
guards, and the obs/locks.py watchdog (intentional inversion detected;
watchdog-off locks are plain threading primitives and the training
numerics are bit-identical either way)."""

import ast
import textwrap
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from split_learning_tpu.analysis import cfg as cfg_mod
from split_learning_tpu.analysis import engine
from split_learning_tpu.obs import dispatch_debug
from split_learning_tpu.obs import locks, spans
from split_learning_tpu.obs import trace as obs_trace
from split_learning_tpu.obs.metrics import Registry

REPO = Path(__file__).resolve().parent.parent


def _lint(tmp_path, relpath, source):
    p = tmp_path / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return engine.lint_file(str(p))


def _rules(findings, *, waived=None):
    return sorted(f.rule for f in findings
                  if waived is None or f.waived is waived)


# ---------------------------------------------------------------------- #
# SLT001: D2H / blocking under the lock
# ---------------------------------------------------------------------- #

def test_slt001_flags_d2h_under_lock(tmp_path):
    findings = _lint(tmp_path, "runtime/server.py", """
        import numpy as np
        class ServerRuntime:
            def step(self):
                with self._lock:
                    g = np.asarray(self.dev)
                    loss = float(self.loss_dev)
                    self.fut.result()
                return g, loss
    """)
    assert _rules(findings) == ["SLT001", "SLT001", "SLT001"]


def test_slt001_negative_shapes(tmp_path):
    findings = _lint(tmp_path, "runtime/server.py", """
        import numpy as np
        import jax.numpy as jnp
        class ServerRuntime:
            def step(self):
                with self._lock:
                    acts = jnp.asarray(self.host)   # H2D: allowed
                    if not self.overlap:
                        g = np.asarray(self.dev)    # gated legacy branch
                g = np.asarray(self.dev)            # off-lock
                return g
            def wait_ok(self):
                with self._cond:
                    while not self.ready:
                        self._cond.wait(timeout=1.0)  # the held cond itself
        class _GroupD2H:
            def _materialize(self):
                with self._lock:                    # the D2H latch
                    self.g = np.asarray(self._g_dev)
    """)
    assert findings == []


def test_slt001_out_of_scope_dir(tmp_path):
    findings = _lint(tmp_path, "models/thing.py", """
        import numpy as np
        class M:
            def f(self):
                with self._lock:
                    return np.asarray(self.dev)
    """)
    assert findings == []


def test_slt001_inline_waiver_same_line_and_line_above(tmp_path):
    findings = _lint(tmp_path, "runtime/server.py", """
        import numpy as np
        class ServerRuntime:
            def a(self):
                with self._lock:
                    g = np.asarray(self.dev)  # slt-lint: disable=SLT001 (demo)
                return g
            def b(self):
                with self._lock:
                    # slt-lint: disable=SLT001 (next-line demo)
                    g = np.asarray(self.dev)
                return g
    """)
    assert _rules(findings, waived=True) == ["SLT001", "SLT001"]
    assert _rules(findings, waived=False) == []


def test_waiver_without_reason_is_itself_a_finding(tmp_path):
    findings = _lint(tmp_path, "runtime/server.py", """
        import numpy as np
        class ServerRuntime:
            def a(self):
                with self._lock:
                    g = np.asarray(self.dev)  # slt-lint: disable=SLT001 ()
                return g
    """)
    rules = _rules(findings, waived=False)
    assert "SLT000" in rules and "SLT001" in rules  # waiver void, both red


# ---------------------------------------------------------------------- #
# SLT002: claim pairing through the CFG
# ---------------------------------------------------------------------- #

def test_slt002_early_return_leaks_claim(tmp_path):
    findings = _lint(tmp_path, "runtime/server.py", """
        class S:
            def step(self, step):
                entry, owner = self.replay.begin(0, "op", step)
                if not owner:
                    return self.replay.wait(entry)
                res = self.compute()
                if res is None:
                    return None
                self.replay.resolve(entry, res)
                return res
    """)
    assert _rules(findings) == ["SLT002"]


def test_slt002_try_except_pairing_is_clean(tmp_path):
    findings = _lint(tmp_path, "runtime/server.py", """
        class S:
            def step(self, step):
                entry, owner = self.replay.begin(0, "op", step)
                if not owner:
                    return self.replay.wait(entry)
                try:
                    res = self.compute()
                    if entry is not None:
                        self.replay.resolve(entry, res)
                    return res
                except BaseException as exc:
                    if entry is not None:
                        self.replay.fail(entry, exc)
                    raise
    """)
    assert findings == []


def test_slt002_resolve_in_finally_is_clean(tmp_path):
    findings = _lint(tmp_path, "runtime/server.py", """
        class S:
            def step(self, step):
                entry, owner = self.replay.begin(0, "op", step)
                try:
                    return self.compute()
                finally:
                    self.replay.resolve(entry, None)
    """)
    assert findings == []


def test_slt002_finally_without_resolve_leaks(tmp_path):
    findings = _lint(tmp_path, "runtime/server.py", """
        class S:
            def step(self, step):
                entry, owner = self.replay.begin(0, "op", step)
                try:
                    return self.compute()
                finally:
                    self.log("done")
    """)
    assert _rules(findings) == ["SLT002"]


def test_slt002_typed_handler_can_leak_past_handlers(tmp_path):
    # a KeyError handler does not catch a RuntimeError: the exceptional
    # edge escapes the try and the claim leaks
    findings = _lint(tmp_path, "runtime/server.py", """
        class S:
            def step(self, step):
                entry, owner = self.replay.begin(0, "op", step)
                try:
                    res = self.compute()
                except KeyError as exc:
                    self.replay.fail(entry, exc)
                    raise
                self.replay.resolve(entry, res)
                return res
    """)
    assert _rules(findings) == ["SLT002"]


def test_cfg_routes_return_through_finally():
    fn = ast.parse(textwrap.dedent("""
        def f(self):
            try:
                return self.work()
            finally:
                self.cleanup()
    """)).body[0]
    graph = cfg_mod.build(fn)
    ret = next(n for n in graph.nodes if isinstance(n.stmt, ast.Return))
    # the return's successor is a duplicated finally statement, not EXIT
    succs = [t for t, _c in ret.succs]
    assert graph.exit not in succs
    assert any(isinstance(t.stmt, ast.Expr) for t in succs)


def test_cfg_early_return_reaches_exit_directly():
    fn = ast.parse(textwrap.dedent("""
        def f(self, x):
            if x is None:
                return 0
            return 1
    """)).body[0]
    graph = cfg_mod.build(fn)
    returns = [n for n in graph.nodes if isinstance(n.stmt, ast.Return)]
    assert len(returns) == 2
    for r in returns:
        assert graph.exit in [t for t, _c in r.succs]


# ---------------------------------------------------------------------- #
# SLT003: span literals
# ---------------------------------------------------------------------- #

def test_slt003_flags_literal_and_accepts_constant(tmp_path):
    findings = _lint(tmp_path, "runtime/worker.py", """
        from split_learning_tpu.obs import spans
        def go(tr, stats, reg, dt):
            tr.record("client_fwd", 0.0, dt)
            stats.record_span("wire", dt)
            reg.observe("lock_hold", dt)
            tr.record(spans.CLIENT_FWD, 0.0, dt)
            stats.record(dt)
    """)
    assert _rules(findings) == ["SLT003", "SLT003", "SLT003"]


def test_slt003_waiver(tmp_path):
    findings = _lint(tmp_path, "runtime/worker.py", """
        def go(tr, dt):
            tr.record("legacy", 0.0, dt)  # slt-lint: disable=SLT003 (old export)
    """)
    assert _rules(findings, waived=True) == ["SLT003"]
    assert _rules(findings, waived=False) == []


# ---------------------------------------------------------------------- #
# SLT004: wire-path determinism
# ---------------------------------------------------------------------- #

def test_slt004_flags_global_rng_unseeded_ctor_and_wall_clock(tmp_path):
    findings = _lint(tmp_path, "ops/noise.py", """
        import random
        import time
        import numpy as np
        def draw():
            a = random.random()
            rs = np.random.RandomState()
            b = np.random.rand(3)
            t = time.time()
            return a, rs, b, t
    """)
    assert _rules(findings) == ["SLT004"] * 4


def test_slt004_seeded_and_measurement_clocks_are_clean(tmp_path):
    findings = _lint(tmp_path, "transport/chaos.py", """
        import random
        import time
        import numpy as np
        def draw(seed):
            rng = random.Random(seed)
            rs = np.random.RandomState(seed & 0x7FFFFFFF)
            t0 = time.perf_counter()
            time.sleep(0.0)
            return rng.random(), rs.rand(), time.monotonic() - t0
    """)
    assert findings == []


def test_slt004_flags_nondet_import(tmp_path):
    findings = _lint(tmp_path, "transport/codec.py", """
        from random import shuffle
    """)
    assert _rules(findings) == ["SLT004"]


def test_slt004_out_of_scope(tmp_path):
    findings = _lint(tmp_path, "launch/cli.py", """
        import time
        def now():
            return time.time()
    """)
    assert findings == []


# ---------------------------------------------------------------------- #
# SLT005: lock-order cycles
# ---------------------------------------------------------------------- #

def test_slt005_direct_cycle(tmp_path):
    findings = _lint(tmp_path, "runtime/sharded.py", """
        class S:
            def a(self):
                with self._alpha_lock:
                    with self._beta_lock:
                        pass
            def b(self):
                with self._beta_lock:
                    with self._alpha_lock:
                        pass
    """)
    assert _rules(findings) == ["SLT005"]


def test_slt005_transitive_cycle_through_method_call(tmp_path):
    findings = _lint(tmp_path, "runtime/sharded.py", """
        class S:
            def outer(self):
                with self._alpha_lock:
                    self.inner()
            def inner(self):
                with self._beta_lock:
                    pass
            def rev(self):
                with self._beta_lock:
                    with self._alpha_lock:
                        pass
    """)
    assert _rules(findings) == ["SLT005"]


def test_slt005_consistent_order_is_clean(tmp_path):
    findings = _lint(tmp_path, "runtime/sharded.py", """
        class S:
            def a(self):
                with self._alpha_lock:
                    with self._beta_lock:
                        pass
            def b(self):
                with self._alpha_lock:
                    with self._beta_lock:
                        pass
    """)
    assert findings == []


# ---------------------------------------------------------------------- #
# SLT006: use-after-donate
# ---------------------------------------------------------------------- #

def test_slt006_read_after_donate(tmp_path):
    findings = _lint(tmp_path, "runtime/trainer.py", """
        import jax
        class T:
            def __init__(self, step_fn):
                self._step = jax.jit(step_fn, donate_argnums=(0,))
            def train(self, state, x):
                new_state, loss = self._step(state, x)
                norm = state.norm()
                return new_state, loss, norm
    """)
    assert _rules(findings) == ["SLT006"]
    assert "donate_argnums" in findings[0].message


def test_slt006_rebind_over_donation_is_clean(tmp_path):
    findings = _lint(tmp_path, "runtime/trainer.py", """
        import jax
        class T:
            def __init__(self, step_fn):
                self._step = jax.jit(step_fn, donate_argnums=(0,))
            def train(self, state, x):
                state, loss = self._step(state, x)
                return state.norm(), loss
            def fresh_then_read(self, state, x):
                new_state, loss = self._step(state, x)
                state = new_state
                return state.norm(), loss
    """)
    assert findings == []


def test_slt006_inline_waiver(tmp_path):
    findings = _lint(tmp_path, "runtime/trainer.py", """
        import jax
        class T:
            def __init__(self, step_fn):
                self._step = jax.jit(step_fn, donate_argnums=(0,))
            def train(self, state, x):
                new_state, loss = self._step(state, x)
                norm = state.norm()  # slt-lint: disable=SLT006 (demo)
                return new_state, loss, norm
    """)
    assert _rules(findings, waived=True) == ["SLT006"]
    assert _rules(findings, waived=False) == []


# ---------------------------------------------------------------------- #
# SLT007: retrace hazards
# ---------------------------------------------------------------------- #

def test_slt007_jit_closure_over_mutable_self_attr(tmp_path):
    findings = _lint(tmp_path, "runtime/trainer.py", """
        import jax
        class T:
            def __init__(self):
                def step(x):
                    return x * self._scale
                self._step = jax.jit(step)
            def set_scale(self, s):
                self._scale = s
    """)
    assert _rules(findings) == ["SLT007"]
    assert "_scale" in findings[0].message


def test_slt007_varying_literals_and_nonhashable_static(tmp_path):
    findings = _lint(tmp_path, "ops/kern.py", """
        import jax
        def f(x, n):
            return x * n
        _g = jax.jit(f)
        _h = jax.jit(f, static_argnums=(1,))
        def a(x):
            return _g(x, 2)
        def b(x):
            return _g(x, 3)
        def c(x):
            return _h(x, 2)
        def d(x):
            return _h(x, 3)
        def e(x):
            return _h(x, [1, 2])
    """)
    # _g varies a traced literal; _h's variation is static (fine) but
    # the list literal at a static position is non-hashable
    assert _rules(findings) == ["SLT007", "SLT007"]


def test_slt007_immutable_attr_and_same_literal_are_clean(tmp_path):
    findings = _lint(tmp_path, "runtime/trainer.py", """
        import jax
        class T:
            def __init__(self, lr):
                self._lr = lr
                def step(x):
                    return x * self._lr
                self._step = jax.jit(step)
            def go(self, x):
                return self._step(x)
    """)
    assert findings == []


# ---------------------------------------------------------------------- #
# SLT008: implicit host sync on traced values
# ---------------------------------------------------------------------- #

def test_slt008_branch_bool_and_scalar_before_bulk(tmp_path):
    findings = _lint(tmp_path, "runtime/worker.py", """
        import jax
        import numpy as np
        def step_fn(x):
            return x
        _step = jax.jit(step_fn)
        class R:
            def brancher(self, x):
                loss = _step(x)
                if loss:
                    return 0.0
                return 1.0
            def boolsync(self, x):
                loss = _step(x)
                return bool(loss)
            def eager_scalar(self, x):
                g, loss = _step(x)
                l = float(loss)
                gh = np.asarray(g)
                return gh, l
    """)
    assert _rules(findings) == ["SLT008", "SLT008", "SLT008"]


def test_slt008_bulk_first_and_lone_scalar_are_clean(tmp_path):
    findings = _lint(tmp_path, "runtime/worker.py", """
        import jax
        import numpy as np
        def step_fn(x):
            return x
        _step = jax.jit(step_fn)
        class R:
            def drained(self, x):
                g, loss = _step(x)
                gh = np.asarray(g)
                return gh, float(loss)
            def lone_scalar(self, x):
                loss = _step(x)
                return float(loss)
            def host_if(self, x):
                loss = _step(x)
                loss = float(loss)
                if loss:
                    return 0.0
                return loss
    """)
    assert findings == []


# ---------------------------------------------------------------------- #
# SLT009: PRNG key discipline
# ---------------------------------------------------------------------- #

def test_slt009_double_consumption_and_loop_reuse(tmp_path):
    findings = _lint(tmp_path, "ops/noise.py", """
        import jax
        def double(key, shape):
            a = jax.random.normal(key, shape)
            b = jax.random.uniform(key, shape)
            return a + b
        def loopy(key, xs):
            out = 0.0
            for x in xs:
                out = out + jax.random.normal(key, x.shape)
            return out
    """)
    assert _rules(findings) == ["SLT009", "SLT009"]


def test_slt009_split_and_fold_in_are_clean(tmp_path):
    findings = _lint(tmp_path, "ops/noise.py", """
        import jax
        def ok(key, shape):
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1, shape)
            b = jax.random.normal(k2, shape)
            return a + b
        def per_step(key, xs):
            out = 0.0
            for i, x in enumerate(xs):
                k = jax.random.fold_in(key, i)
                out = out + jax.random.normal(k, x.shape)
            return out
    """)
    assert findings == []


# ---------------------------------------------------------------------- #
# SLT010: wire-schema contract (project rule, cross-file)
# ---------------------------------------------------------------------- #

def _lint_tree(tmp_path, files, waiver_text=None):
    for rel, srctext in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(srctext))
    wf = None
    if waiver_text is not None:
        wfp = tmp_path / "waivers"
        wfp.write_text(waiver_text)
        wf = str(wfp)
    return engine.lint_paths([str(tmp_path)], waiver_file=wf)


_CODEC_DRIFT = {"transport/codec.py": """
    def foo_compress(arr):
        return {"tag": True, "n": 3, "ghost": 1}
    def foo_decompress(d):
        return (d["tag"], d["n"], d["missing"])
"""}


def test_slt010_codec_field_drift_both_directions(tmp_path):
    findings = _lint_tree(tmp_path, _CODEC_DRIFT)
    assert _rules(findings) == ["SLT010", "SLT010"]
    msgs = " ".join(f.message for f in findings)
    assert "ghost" in msgs and "missing" in msgs


def test_slt010_matched_codec_is_clean(tmp_path):
    findings = _lint_tree(tmp_path, {"transport/codec.py": """
        def foo_compress(arr):
            return {"tag": True, "n": 3}
        def foo_decompress(d):
            return (d["tag"], d["n"])
    """})
    assert findings == []


def test_slt010_http_reply_field_never_read(tmp_path):
    findings = _lint_tree(tmp_path, {"transport/http.py": """
        class HttpTransport:
            def split_step(self, acts, step):
                out = self._post("/forward_pass",
                                 {"acts": acts, "step": step})
                return out["grads"], float(out["loss"])
        def handle_forward(req, runtime):
            grads, loss = runtime.split_step(req["acts"], req["step"])
            resp = {"grads": grads, "loss": loss, "debug": 1}
            return resp
    """})
    assert _rules(findings) == ["SLT010"]
    assert "debug" in findings[0].message


def test_slt010_native_binding_pairing(tmp_path):
    cc = (tmp_path / "native")
    cc.mkdir(parents=True, exist_ok=True)
    (cc / "slt_codec.cc").write_text(
        'extern "C" {\n'
        "int slt_encode(const char* buf) {\n  return 0;\n}\n"
        "int slt_unused(int x) {\n  return 1;\n}\n"
        "}\n")
    findings = _lint_tree(tmp_path, {"native/codec.py": """
        lib = None
        def encode(buf):
            return lib.slt_encode(buf)
        def missing(buf):
            return lib.slt_missing(buf)
    """})
    assert _rules(findings) == ["SLT010", "SLT010"]
    msgs = " ".join(f.message for f in findings)
    assert "slt_missing" in msgs and "slt_unused" in msgs


def test_slt010_waiver_file(tmp_path):
    for rel, srctext in _CODEC_DRIFT.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(srctext))
    wf = tmp_path / "waivers"
    wf.write_text("SLT010 transport/codec.py legacy peer still sends it\n")
    assert engine.main([str(tmp_path), "--waiver-file", str(wf)]) == 0
    assert engine.main([str(tmp_path)]) == 1


# ---------------------------------------------------------------------- #
# SLT011: condition wait() outside a while-predicate loop
# ---------------------------------------------------------------------- #

def test_slt011_bare_and_if_guarded_wait(tmp_path):
    findings = _lint(tmp_path, "runtime/coalesce.py", """
        class Coalescer:
            def a(self):
                with self._cond:
                    self._cond.wait(timeout=1.0)      # bare: flagged
            def b(self):
                with self.cv:
                    if not self.ready:
                        self.cv.wait()                # if-guard: flagged
    """)
    assert _rules(findings) == ["SLT011", "SLT011"]
    msgs = " ".join(f.message for f in findings)
    assert "while" in msgs


def test_slt011_while_wrapped_and_wait_for_are_clean(tmp_path):
    findings = _lint(tmp_path, "runtime/coalesce.py", """
        class Coalescer:
            def a(self):
                with self._cond:
                    while not self.ready:
                        self._cond.wait(timeout=1.0)
            def b(self):
                with self.cv:
                    self.cv.wait_for(lambda: self.ready, timeout=1.0)
            def c(self):
                while True:
                    with self._cond:
                        self._cond.wait()   # enclosing while counts
    """)
    assert findings == []


def test_slt011_nested_def_resets_loop_scope(tmp_path):
    # the while loop belongs to the outer function; a wait() inside a
    # nested def is NOT protected by it
    findings = _lint(tmp_path, "runtime/fleet.py", """
        class Fleet:
            def run(self):
                while self.alive:
                    def poke():
                        with self._cond:
                            self._cond.wait()
                    poke()
    """)
    assert _rules(findings) == ["SLT011"]


def test_slt011_scoped_to_runtime_and_transport(tmp_path):
    findings = _lint(tmp_path, "examples/demo.py", """
        class Demo:
            def f(self):
                with self._cond:
                    self._cond.wait()
    """)
    assert findings == []


def test_slt011_waiver_file(tmp_path):
    bad = tmp_path / "runtime" / "coalesce.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent("""
        class C:
            def f(self):
                with self._cond:
                    self._cond.wait()
    """))
    wf = tmp_path / "waivers"
    wf.write_text("SLT011 runtime/coalesce.py single-waiter, "
                  "timeout-bounded\n")
    assert engine.main([str(tmp_path), "--waiver-file", str(wf)]) == 0
    assert engine.main([str(tmp_path)]) == 1


# ---------------------------------------------------------------------- #
# SLT012: state.params reads on a deferred-apply runtime need the lock
# ---------------------------------------------------------------------- #

def test_slt012_unlocked_params_read_flagged(tmp_path):
    findings = _lint(tmp_path, "runtime/server.py", """
        class ServerRuntime:
            def __init__(self):
                self._deferred = object()
            def peek(self):
                return self.state.params          # unlocked: flagged
            def hook(self):
                def cb():
                    return self.state.params      # nested def: flagged
                return cb
    """)
    assert _rules(findings) == ["SLT012", "SLT012"]
    msgs = " ".join(f.message for f in findings)
    assert "apply lock" in msgs and "export_state" in msgs


def test_slt012_locked_and_barrier_reads_are_clean(tmp_path):
    findings = _lint(tmp_path, "runtime/server.py", """
        class ServerRuntime:
            def __init__(self):
                self._deferred = object()
            def locked(self):
                with self._lock:
                    return self.state.params
            def export_state(self):
                self._deferred.flush()
                return self.state.params          # the flush barrier
            def flush_deferred(self):
                return self.state.params
    """)
    assert findings == []


def test_slt012_scoped_to_deferred_owning_classes(tmp_path):
    # a runtime class WITHOUT a deferred queue has no stale-params
    # hazard — its unlocked reads stay legal (the client trainer shape)
    findings = _lint(tmp_path, "runtime/client.py", """
        class SplitClientTrainer:
            def loss_params(self):
                return self.state.params
    """)
    assert findings == []
    # ...and files outside runtime/ are out of scope entirely
    findings = _lint(tmp_path, "launch/run.py", """
        class Driver:
            def __init__(self):
                self._deferred = object()
            def peek(self):
                return self.state.params
    """)
    assert findings == []


def test_slt012_waiver_file(tmp_path):
    bad = tmp_path / "runtime" / "server.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent("""
        class ServerRuntime:
            def __init__(self):
                self._deferred = object()
            def peek(self):
                return self.state.params
    """))
    wf = tmp_path / "waivers"
    wf.write_text("SLT012 runtime/server.py read-only debug probe\n")
    assert engine.main([str(tmp_path), "--waiver-file", str(wf)]) == 0
    assert engine.main([str(tmp_path)]) == 1


# ---------------------------------------------------------------------- #
# SLT013: sharded outputs cross D2H via the sanctioned per-shard gather
# ---------------------------------------------------------------------- #

def test_slt013_raw_gather_in_expected_d2h_flagged(tmp_path):
    findings = _lint(tmp_path, "runtime/server.py", """
        import numpy as np
        import jax
        class ServerRuntime:
            def __init__(self):
                self._mesh = object()
            def step(self, tag):
                with obs_dispatch.expected_d2h(tag):
                    g = np.asarray(self.g_dev)       # raw shard gather
                    e = np.array(self.e_dev)         # same, via np.array
                    h = jax.device_get(self.h_dev)   # same, via jax
                return g, e, h
    """)
    assert _rules(findings) == ["SLT013", "SLT013", "SLT013"]
    msgs = " ".join(f.message for f in findings)
    assert "_host_gather" in msgs and "per-shard" in msgs


def test_slt013_sanctioned_gather_and_off_path_reads_clean(tmp_path):
    findings = _lint(tmp_path, "runtime/server.py", """
        import numpy as np
        class ServerRuntime:
            def __init__(self):
                self._mesh = object()
            def step(self, tag):
                with obs_dispatch.expected_d2h(tag):
                    g = self._host_gather(self.g_dev)   # the seam
                    cb = lambda: np.asarray(self.x)     # runs later
                n = np.asarray(self.host_buf)           # outside the block
                return g, cb, n
    """)
    assert findings == []


def test_slt013_scoped_to_mesh_aware_runtime_classes(tmp_path):
    # a runtime class with NO mesh attributes has single-device outputs
    # — np.asarray on them is the normal (and correct) materialization
    findings = _lint(tmp_path, "runtime/client.py", """
        import numpy as np
        class SplitClientTrainer:
            def step(self, tag):
                with obs_dispatch.expected_d2h(tag):
                    return np.asarray(self.g_dev)
    """)
    assert findings == []
    # ...and files outside runtime/ are out of scope entirely
    findings = _lint(tmp_path, "launch/run.py", """
        import numpy as np
        class Driver:
            def __init__(self):
                self._mesh = object()
            def step(self, tag):
                with obs_dispatch.expected_d2h(tag):
                    return np.asarray(self.g_dev)
    """)
    assert findings == []


def test_slt013_waiver_file(tmp_path):
    bad = tmp_path / "runtime" / "server.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent("""
        import numpy as np
        class ServerRuntime:
            def __init__(self):
                self._mesh = object()
            def step(self, tag):
                with obs_dispatch.expected_d2h(tag):
                    return np.asarray(self.g_dev)
    """))
    wf = tmp_path / "waivers"
    wf.write_text("SLT013 runtime/server.py replicated-only debug path\n")
    assert engine.main([str(tmp_path), "--waiver-file", str(wf)]) == 0
    assert engine.main([str(tmp_path)]) == 1


# ---------------------------------------------------------------------- #
# SLT014: persistence discipline (crash-atomic writes + field pairing)
# ---------------------------------------------------------------------- #

def test_slt014_flags_in_place_writes(tmp_path):
    findings = _lint(tmp_path, "runtime/ckpt.py", """
        import pickle
        def save_meta(path, text):
            with open(path, "w") as f:
                f.write(text)
        def save_blob(path, obj):
            with open(path, "wb") as f:
                pickle.dump(obj, f)
    """)
    # the bare open(...,'w'), the open(...,'wb'), and pickle.dump
    assert _rules(findings) == ["SLT014", "SLT014", "SLT014"]
    msgs = " ".join(f.message for f in findings)
    assert "rename" in msgs or "atomic" in msgs


def test_slt014_tmp_write_rename_idiom_clean(tmp_path):
    findings = _lint(tmp_path, "runtime/ckpt.py", """
        import os
        def save_meta(path, text):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, path)
        class _OsFS:
            def put(self, path, text):
                with open(path, "w") as f:
                    f.write(text)
            def rename(self, src, dst):
                os.replace(src, dst)
        def read(path):
            with open(path) as f:
                return f.read()
    """)
    assert findings == []
    # files outside runtime/ are out of scope for part A
    findings = _lint(tmp_path, "scripts/dump.py", """
        def save(path, text):
            with open(path, "w") as f:
                f.write(text)
    """)
    assert findings == []


def test_slt014_inline_waiver(tmp_path):
    findings = _lint(tmp_path, "runtime/ckpt.py", """
        def save(path, text):
            with open(path, "w") as f:  # slt-lint: disable=SLT014 (scratch file, rebuilt on boot)
                f.write(text)
    """)
    assert _rules(findings, waived=True) == ["SLT014"]
    assert _rules(findings, waived=False) == []


def test_slt014_waiver_file(tmp_path):
    bad = tmp_path / "runtime" / "ckpt.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent("""
        def save(path, text):
            with open(path, "w") as f:
                f.write(text)
    """))
    wf = tmp_path / "waivers"
    wf.write_text("SLT014 runtime/ckpt.py legacy dump path, migration tracked\n")
    assert engine.main([str(tmp_path), "--waiver-file", str(wf)]) == 0
    assert engine.main([str(tmp_path)]) == 1


def test_slt014_pairing_cross_file(tmp_path):
    # exporter writes "ghost" nobody restores; restorer hard-reads
    # "missing" nobody exports — both cross-file findings
    exp = tmp_path / "runtime" / "state.py"
    exp.parent.mkdir(parents=True)
    exp.write_text(textwrap.dedent("""
        def export_state(self):
            return {"step": 1, "ghost": 2}
    """))
    res = tmp_path / "transport" / "wire.py"
    res.parent.mkdir(parents=True)
    res.write_text(textwrap.dedent("""
        def restore_state(self, rec):
            step = rec["step"]
            val = rec["missing"]
            return step, val
    """))
    findings = [f for f in engine.lint_paths([str(tmp_path)])
                if f.rule == "SLT014"]
    msgs = " ".join(f.message for f in findings)
    assert "ghost" in msgs
    assert "missing" in msgs


def test_slt014_pairing_matched_fields_clean(tmp_path):
    exp = tmp_path / "runtime" / "state.py"
    exp.parent.mkdir(parents=True)
    exp.write_text(textwrap.dedent("""
        def export_state(self):
            return {"step": 1, "replay": []}
    """))
    res = tmp_path / "runtime" / "boot.py"
    res.write_text(textwrap.dedent("""
        def restore_state(self, rec):
            return rec["step"], rec.get("replay", [])
    """))
    findings = [f for f in engine.lint_paths([str(tmp_path)])
                if f.rule == "SLT014"]
    assert findings == []


# ---------------------------------------------------------------------- #
# SLT015: flight event names come from the spans.py FL_* registry
# ---------------------------------------------------------------------- #

def test_slt015_flags_literal_and_unregistered(tmp_path):
    findings = _lint(tmp_path, "runtime/server.py", """
        from split_learning_tpu.obs import flight as obs_flight
        from split_learning_tpu.obs import spans
        def step(self):
            fl = obs_flight.get_recorder()
            if fl is not None:
                fl.record("my_event", step=1)
                fl.record(spans.FL_BOGUS, step=1)
    """)
    rules = _rules(findings)
    # the literal also co-fires SLT003 (same sink, same registry
    # discipline) — SLT015 must flag both the literal and the
    # unregistered constant
    assert rules.count("SLT015") == 2
    msgs = " ".join(f.message for f in findings if f.rule == "SLT015")
    assert "my_event" in msgs and "FL_BOGUS" in msgs


def test_slt015_registered_constant_and_scope_clean(tmp_path):
    findings = _lint(tmp_path, "runtime/server.py", """
        from split_learning_tpu.obs import spans
        def step(self, fl):
            if fl is not None:
                fl.record(spans.FL_DISPATCH, step=3, client_id=0)
        def trace(self, tr):
            tr.record(spans.DISPATCH, 0.0, 0.1)
    """)
    assert [f for f in findings if f.rule == "SLT015"] == []
    # non-flight receivers and out-of-scope dirs never fire
    findings = _lint(tmp_path, "models/demo.py", """
        def f(fl):
            fl.record("free_text")
    """)
    assert [f for f in findings if f.rule == "SLT015"] == []


def test_slt015_inline_waiver(tmp_path):
    findings = _lint(tmp_path, "transport/wire.py", """
        def f(fl):
            fl.record(FL_EXPERIMENTAL)  # slt-lint: disable=SLT015 (prototype event, registered next PR)
    """)
    assert _rules(findings, waived=True) == ["SLT015"]
    assert _rules(findings, waived=False) == []


# ---------------------------------------------------------------------- #
# engine: exit codes, waiver file, real tree
# ---------------------------------------------------------------------- #

def test_main_exit_codes(tmp_path, capsys):
    bad = tmp_path / "runtime" / "server.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent("""
        import numpy as np
        class ServerRuntime:
            def f(self):
                with self._lock:
                    return np.asarray(self.dev)
    """))
    assert engine.main([str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "SLT001" in out and "server.py:6" in out  # file:line carried
    bad.write_text("x = 1\n")
    assert engine.main([str(tmp_path)]) == 0


def test_waiver_file_scoped_waiver(tmp_path):
    bad = tmp_path / "runtime" / "server.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent("""
        import numpy as np
        class ServerRuntime:
            def f(self):
                with self._lock:
                    return np.asarray(self.dev)
    """))
    wf = tmp_path / "waivers"
    wf.write_text("SLT001 runtime/server.py quarantined pending refactor\n")
    assert engine.main([str(tmp_path), "--waiver-file", str(wf)]) == 0
    wf.write_text("SLT001\n")  # malformed: no path/reason
    assert engine.main([str(tmp_path), "--waiver-file", str(wf)]) == 1


def test_syntax_error_is_a_finding(tmp_path):
    p = tmp_path / "runtime" / "broken.py"
    p.parent.mkdir(parents=True)
    p.write_text("def f(:\n")
    findings = engine.lint_file(str(p))
    assert _rules(findings) == ["SLT000"]


def test_list_rules(capsys):
    assert engine.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("SLT001", "SLT002", "SLT003", "SLT004", "SLT005",
                 "SLT006", "SLT007", "SLT008", "SLT009", "SLT010",
                 "SLT011", "SLT012", "SLT013", "SLT014", "SLT015",
                 # slt-check dynamic-invariant pseudo-rules
                 "SLT100", "SLT101", "SLT102", "SLT103", "SLT104",
                 "SLT105", "SLT106", "SLT107", "SLT108",
                 # slt-crash durability invariants
                 "SLT109", "SLT110", "SLT111", "SLT112"):
        assert rule in out


def test_real_tree_has_zero_unwaived_findings():
    """The acceptance gate: the shipped tree lints clean."""
    findings = engine.lint_paths([str(REPO / "split_learning_tpu"),
                                  str(REPO / "scripts")],
                                 waiver_file=str(REPO / ".slt-lint.waivers"))
    unwaived = [f for f in findings if not f.waived]
    assert unwaived == [], "\n".join(f.format() for f in unwaived)


# ---------------------------------------------------------------------- #
# spans registry: drift guards
# ---------------------------------------------------------------------- #

def test_trace_reexports_spans_tuples():
    assert obs_trace.CLIENT_PHASES == spans.CLIENT_PHASES
    assert obs_trace.SERVER_PHASES == spans.SERVER_PHASES


def test_trace_report_fallback_matches_registry():
    """scripts/trace_report.py runs standalone, so it keeps a literal
    fallback copy of the phase tuples — pinned here to the registry."""
    tree = ast.parse((REPO / "scripts" / "trace_report.py").read_text())
    fallback = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        for h in node.handlers:
            if getattr(h.type, "id", None) != "ImportError":
                continue
            for s in h.body:
                if (isinstance(s, ast.Assign)
                        and isinstance(s.targets[0], ast.Name)):
                    fallback[s.targets[0].id] = ast.literal_eval(s.value)
    assert fallback["CLIENT_PHASES"] == spans.CLIENT_PHASES
    assert fallback["TRANSPORT_SUB"] == spans.TRANSPORT_SUB
    assert fallback["COMPILE"] == spans.COMPILE
    assert fallback["REPLY_GRAD"] == spans.REPLY_GRAD
    assert fallback["DEFERRED_APPLY"] == spans.DEFERRED_APPLY
    assert fallback["MESH_META"] == spans.MESH_META
    assert fallback["STAGE_META"] == spans.STAGE_META


def test_postmortem_fallback_matches_registry():
    """scripts/postmortem.py runs standalone too: its ImportError
    fallback of FL_* event names is pinned byte-equal to the
    obs/spans.py registry."""
    tree = ast.parse((REPO / "scripts" / "postmortem.py").read_text())
    fallback = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        for h in node.handlers:
            if getattr(h.type, "id", None) != "ImportError":
                continue
            for s in h.body:
                if (isinstance(s, ast.Assign)
                        and isinstance(s.targets[0], ast.Name)):
                    fallback[s.targets[0].id] = ast.literal_eval(s.value)
    assert fallback, "postmortem.py lost its ImportError fallback"
    registered = {k for k in vars(spans) if k.startswith("FL_")}
    assert set(fallback) <= registered
    for name, value in fallback.items():
        assert getattr(spans, name) == value, name


def test_analysis_package_is_stdlib_only():
    """The CI lint step must not need jax/numpy: the analysis package
    imports nothing outside the stdlib and itself."""
    import importlib
    # sched/invariants are pinned too: the model checker itself must
    # run on the lint image (scenarios.py is the one module allowed to
    # import numpy/the runtime, and the engine only loads it lazily
    # under --check)
    for mod in ("engine", "rules", "rules_jax", "cfg", "sched",
                "invariants"):
        m = importlib.import_module(f"split_learning_tpu.analysis.{mod}")
        src = Path(m.__file__).read_text()
        tree = ast.parse(src)
        for node in ast.walk(tree):
            names = []
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                names = [node.module]
            for name in names:
                root = name.split(".")[0]
                assert root not in ("jax", "numpy", "requests"), (
                    f"{mod}.py imports {name}")


# ---------------------------------------------------------------------- #
# obs/locks.py: the dynamic watchdog
# ---------------------------------------------------------------------- #

def test_intentional_inversion_is_detected():
    g = locks.LockGraph()
    a = locks.InstrumentedLock("A", graph=g, budget_s=None)
    b = locks.InstrumentedLock("B", graph=g, budget_s=None)
    with a:
        with b:
            pass
    assert g.violations == []  # one order alone is fine
    with b:
        with a:
            pass
    kinds = [v["kind"] for v in g.violations]
    assert kinds == ["lock-order-inversion"]
    msg = g.violations[0]["message"]
    assert "A" in msg and "B" in msg
    # repeated inversions of the same pair are reported once
    with b:
        with a:
            pass
    assert len(g.violations) == 1


def test_hold_budget_violation():
    g = locks.LockGraph()
    h = locks.InstrumentedLock("H", graph=g, budget_s=0.001)
    with h:
        time.sleep(0.01)
    assert [v["kind"] for v in g.violations] == ["hold-budget"]
    ok = locks.InstrumentedLock("OK", graph=g, budget_s=10.0)
    with ok:
        pass
    assert len(g.violations) == 1


def test_reentrant_acquire_is_not_an_edge_and_hold_spans_outermost():
    g = locks.LockGraph()
    reg = Registry()
    l = locks.InstrumentedLock("R", graph=g, registry=reg, budget_s=None)
    with l:
        with l:  # reentrant
            pass
    assert g.violations == [] and g.edges == {}
    snap = reg.snapshot()["histograms"]
    assert snap[spans.LOCK_HOLD]["count"] == 1  # one outermost hold


def test_condition_interop():
    g = locks.LockGraph()
    cv = threading.Condition(locks.InstrumentedLock("CV", graph=g,
                                                    budget_s=None))
    hits = []

    def waiter():
        with cv:
            while not hits:
                cv.wait(timeout=5.0)
            hits.append("woke")

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cv:
        hits.append("notified")
        cv.notify()
    t.join(timeout=5.0)
    assert not t.is_alive() and hits == ["notified", "woke"]
    assert g.violations == []


def test_make_lock_off_returns_plain_threading_primitives(monkeypatch):
    monkeypatch.delenv("SLT_LOCK_DEBUG", raising=False)
    assert isinstance(locks.make_lock("x"), type(threading.RLock()))
    assert isinstance(locks.make_lock("x", reentrant=False),
                      type(threading.Lock()))


def test_make_lock_on_instruments_runtime_components(monkeypatch):
    monkeypatch.setenv("SLT_LOCK_DEBUG", "1")
    from split_learning_tpu.runtime.coalesce import RequestCoalescer
    from split_learning_tpu.runtime.replay import ReplayCache
    assert isinstance(locks.make_lock("x"), locks.InstrumentedLock)
    cache = ReplayCache()
    assert isinstance(cache._lock, locks.InstrumentedLock)
    co = RequestCoalescer(lambda group, reason: None, max_group=2,
                          window_s=0.0)
    try:
        assert isinstance(co._cond._lock, locks.InstrumentedLock)
    finally:
        co.close()


def test_watchdog_loss_series_bit_identical(monkeypatch):
    """SLT_LOCK_DEBUG instruments the locks and nothing else: the same
    three steps produce a bit-identical loss series on and off — and
    the off path (the shipped default) uses plain threading locks, so
    the wire cannot change."""
    from split_learning_tpu.models import get_plan
    from split_learning_tpu.runtime import ServerRuntime, SplitClientTrainer
    from split_learning_tpu.transport.local import LocalTransport
    from split_learning_tpu.utils import Config

    def series(debug):
        if debug:
            monkeypatch.setenv("SLT_LOCK_DEBUG", "1")
        else:
            monkeypatch.delenv("SLT_LOCK_DEBUG", raising=False)
        cfg = Config(mode="split", batch_size=4, num_clients=1)
        plan = get_plan(mode="split")
        sample = np.zeros((4, 28, 28, 1), np.float32)
        server = ServerRuntime(plan, cfg, jax.random.PRNGKey(2), sample)
        if debug:
            assert isinstance(server._lock, locks.InstrumentedLock)
        else:
            assert isinstance(server._lock, type(threading.RLock()))
        client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                    LocalTransport(server))
        rs = np.random.RandomState(7)
        try:
            return [client.train_step(
                rs.randn(4, 28, 28, 1).astype(np.float32),
                rs.randint(0, 10, 4).astype(np.int64), i)
                for i in range(3)]
        finally:
            server.close()

    on = series(True)
    assert locks.default_graph().violations == []
    assert on == series(False)


# ---------------------------------------------------------------------- #
# obs/dispatch_debug.py: the dispatch watchdog
# ---------------------------------------------------------------------- #

def _with_listener(t):
    """Feed jax.monitoring compile events into a private tracker; the
    returned callable detaches it (best-effort: the unregister hook is
    a private API)."""
    def listener(event, secs, **_kw):
        t.on_compile_event(event, secs)
    jax.monitoring.register_event_duration_secs_listener(listener)

    def detach():
        try:
            from jax._src import monitoring as _mon
            _mon._unregister_event_duration_listener_by_callback(listener)
        except Exception:
            pass
    return detach


def test_dispatch_tracker_flags_steady_state_recompile():
    """A jit whose static arg varies per step compiles on EVERY call;
    from local ordinal 2 on, with the signature already seen, each one
    is a steady-state-recompile violation (deduped per ordinal)."""
    t = dispatch_debug.DispatchTracker()
    detach = _with_listener(t)
    try:
        f = jax.jit(lambda x, n: x * n, static_argnums=(1,))
        x = jnp.ones((4,), jnp.float32)
        for i in range(5):
            with t.scope(("trainer", "step"), sig=(x.shape, "float32")):
                f(x, i).block_until_ready()
    finally:
        detach()
    assert t.compile_count >= 5  # one real backend compile per call
    kinds = [v["kind"] for v in t.violations]
    assert kinds == ["steady-state-recompile"] * 3  # ordinals 2, 3, 4
    assert t.gauges()["steady_state_recompiles"] == 3.0
    assert t.gauges()["compile_count"] == float(t.compile_count)


def test_dispatch_tracker_fresh_signature_is_exempt():
    """New input shapes legitimately compile at any ordinal — the
    signature set marks those scopes fresh and nothing is flagged."""
    t = dispatch_debug.DispatchTracker()
    detach = _with_listener(t)
    try:
        g = jax.jit(lambda x: x * 2.0)
        for n in (3, 4, 5, 6):
            with t.scope("g", sig=((n,), "float32")):
                g(jnp.ones((n,), jnp.float32)).block_until_ready()
    finally:
        detach()
    assert t.compile_count >= 4
    assert t.violations == []


def test_dispatch_guard_error_is_counted_and_reraised():
    """The transfer guard is inert on the CPU backend (module
    docstring), so the reporting path is exercised with a synthetic
    guard-shaped error: counted, reported, re-raised."""
    t = dispatch_debug.DispatchTracker()
    with pytest.raises(RuntimeError):
        with t.scope("k"):
            raise RuntimeError(
                "Disallowed device-to-host transfer: from platform cpu")
    assert t.unexpected_d2h == 1
    assert [v["kind"] for v in t.violations] == ["unexpected-d2h"]
    assert t.gauges()["unexpected_d2h_total"] == 1.0
    with pytest.raises(RuntimeError):  # unrelated errors pass uncounted
        with t.scope("k"):
            raise RuntimeError("boom")
    assert t.unexpected_d2h == 1


def test_dispatch_helpers_off_are_shared_nullcontext(monkeypatch):
    monkeypatch.delenv("SLT_DISPATCH_DEBUG", raising=False)
    assert dispatch_debug.attach() is None
    assert (dispatch_debug.step_scope(None, "k")
            is dispatch_debug.expected_d2h(None))


def test_dispatch_force_enables_attach(monkeypatch):
    externally_on = dispatch_debug.enabled()
    monkeypatch.delenv("SLT_DISPATCH_DEBUG", raising=False)
    dispatch_debug.force(True)
    try:
        t = dispatch_debug.attach()
        assert t is dispatch_debug.tracker()
        assert set(t.gauges()) == {"compile_count",
                                   "unexpected_d2h_total",
                                   "steady_state_recompiles"}
    finally:
        dispatch_debug.force(False)
        if not externally_on:
            dispatch_debug.uninstall()


def test_dispatch_watchdog_loss_series_bit_identical(monkeypatch):
    """SLT_DISPATCH_DEBUG wraps the jitted calls in scopes and nothing
    else: the same three steps produce a bit-identical loss series on
    and off — and on the shipped default (off) every hook is None and
    step_scope/expected_d2h return the shared nullcontext."""
    from split_learning_tpu.models import get_plan
    from split_learning_tpu.runtime import ServerRuntime, SplitClientTrainer
    from split_learning_tpu.transport.local import LocalTransport
    from split_learning_tpu.utils import Config

    externally_on = dispatch_debug.enabled()

    def series(debug):
        if debug:
            monkeypatch.setenv("SLT_DISPATCH_DEBUG", "1")
        else:
            monkeypatch.delenv("SLT_DISPATCH_DEBUG", raising=False)
        cfg = Config(mode="split", batch_size=4, num_clients=1)
        plan = get_plan(mode="split")
        sample = np.zeros((4, 28, 28, 1), np.float32)
        server = ServerRuntime(plan, cfg, jax.random.PRNGKey(2), sample)
        assert (server._dd is not None) is debug
        client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                    LocalTransport(server))
        assert (client._dd is not None) is debug
        rs = np.random.RandomState(7)
        try:
            return [client.train_step(
                rs.randn(4, 28, 28, 1).astype(np.float32),
                rs.randint(0, 10, 4).astype(np.int64), i)
                for i in range(3)]
        finally:
            server.close()

    try:
        on = series(True)
        # steady steps over fixed shapes: no watchdog report
        assert dispatch_debug.tracker().violations == []
        assert on == series(False)
    finally:
        if not externally_on:
            dispatch_debug.uninstall()
