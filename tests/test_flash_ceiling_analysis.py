"""The committed flash MFU ceiling analysis
(``artifacts/flash_ceiling_analysis.json``, VERDICT r4 #8's
documented-ceiling closure) stays self-consistent with the measurement
artifact it derives from."""

import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "artifacts", "flash_ceiling_analysis.json")


@pytest.fixture(scope="module")
def art():
    if not os.path.exists(ARTIFACT):
        pytest.skip(f"missing {ARTIFACT}; run "
                    "scripts/flash_ceiling_analysis.py")
    with open(ARTIFACT) as f:
        return json.load(f)


def test_internally_consistent(art):
    share = art["attention_share_of_dense_flops"]
    rec = art["flash_recompute_factor"]
    assert 0 < share < 1
    assert rec == pytest.approx(14 / 12, rel=1e-3)
    d = art["derived"]
    m = art["measured"]
    est = d["attention_free_estimate_equal_efficiency"]
    cap = d["attention_free_hard_cap"]
    # both attention-free figures dominate the measurement, and the
    # assumption-free cap dominates the assumption-laden estimate
    # (the cap deliberately has no reported-MFU form: the ratio
    # exceeds 1.0 once unexecuted attention FLOPs stay in the
    # numerator — a metric artifact, not a utilization)
    assert est["steps_per_sec"] > m["flash_steps_per_sec"]
    assert cap["steps_per_sec"] > est["steps_per_sec"]
    assert "reported_mfu" not in cap
    assert est["reported_mfu"] > m["flash_reported_mfu"]
    # each figure states what it assumes — the estimate is NOT a bound
    assert "assumption" in est and "profiled" in est["assumption"]
    assert cap["assumption"].startswith("none")
    # hardware MFU counts MORE flops at the same steps/s than reported
    assert d["hardware_mfu_counting_executed_flops"] > \
        m["flash_reported_mfu"]
    # executed-FLOP share folds the recompute into the dense share
    expect = share * rec / (1 - share + share * rec)
    assert d["attention_share_of_executed_flops"] == \
        pytest.approx(expect, rel=1e-3)
    # the conclusion's dense comparator comes from the artifact's own
    # data, never a hardcoded literal
    if m["dense_steps_per_sec"]:
        assert f"{m['dense_steps_per_sec']:.1f}" in art["conclusion"]


def test_derives_from_committed_measurement(art):
    src = os.path.join(REPO, art["provenance"]["measured_from"])
    with open(src) as f:
        measured = json.load(f)
    t = art["provenance"]["shape"]["seq_len"]
    leg = next(l for l in measured["legs"]
               if l.get("seq_len") == t and l.get("attn") == "flash")
    assert art["measured"]["flash_steps_per_sec"] == \
        leg["steps_per_sec"]
    # the traced step is the leg's step (the script enforces <=1% at
    # generation time; pin it here too so a stale artifact fails)
    assert art["flops_per_step_dense_equivalent"] == \
        pytest.approx(leg["flops_per_step"], rel=0.01)


def test_import_is_safe_without_artifacts(tmp_path):
    """An artifact-free checkout (fresh clone, CI) must be able to
    import the script — artifact resolution is lazy, from main(); only
    an actual run may SystemExit on a missing assembly."""
    import importlib.util
    import shutil
    import sys

    scripts = tmp_path / "scripts"
    scripts.mkdir()
    src = os.path.join(REPO, "scripts", "flash_ceiling_analysis.py")
    dst = scripts / "flash_ceiling_analysis.py"
    shutil.copy(src, dst)
    # no tmp_path/artifacts dir at all — the empty-checkout case
    spec = importlib.util.spec_from_file_location("fca_bare", str(dst))
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, REPO)  # its REPO points at tmp; the package must
    try:                      # still resolve from the real checkout
        spec.loader.exec_module(mod)  # must NOT raise
        with pytest.raises(SystemExit, match="no assembled"):
            mod._newest_artifact()
    finally:
        sys.path.remove(REPO)
