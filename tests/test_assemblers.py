"""Selection logic of the incremental measurement assemblers
(scripts/assemble_headline_artifact.py, scripts/assemble_long_context.py):
the rules that decide which opportunistic window-runner record becomes
the committed artifact. Pure-python (no jax) — the expensive end of
these scripts runs on the chip; the part that can rot silently is the
ranking."""

import importlib.util
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    path = os.path.join(REPO, "scripts", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, REPO)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def headline():
    return _load("assemble_headline_artifact")


@pytest.fixture(scope="module")
def longctx():
    return _load("assemble_long_context")


def _rec(leg, status="ok", ts=0.0, valid=True, **result):
    rec = {"leg": leg, "status": status, "ts": ts}
    if status != "oom":
        rec["result"] = {"valid": valid, **result}
    return rec


def test_headline_full_beats_quick_and_newest_wins(headline):
    records = [
        _rec("cnn_headline.q", ts=1, steps_per_sec=100.0),
        _rec("cnn_headline.full", ts=2, steps_per_sec=90.0),
        _rec("cnn_headline.q", ts=3, steps_per_sec=110.0),
    ]
    # a full leg outranks any quick leg regardless of recency
    assert headline.best_leg(records, "cnn_headline.")["steps_per_sec"] == 90.0


def test_headline_skips_invalid_and_non_ok(headline):
    records = [
        _rec("cnn_headline.q", ts=1, steps_per_sec=100.0),
        _rec("cnn_headline.q", ts=2, steps_per_sec=999.0, valid=False),
        _rec("cnn_headline.full", status="timeout", ts=3),
    ]
    assert headline.best_leg(records, "cnn_headline.")["steps_per_sec"] == 100.0
    assert headline.best_leg(records, "decode.") is None


def test_longctx_ok_never_displaced_by_later_failed_full(longctx):
    records = [
        _rec("T1024.b64.flash.q", ts=1, steps_per_sec=45.0,
             seq_len=1024, attn="flash", batch=64),
        {"leg": "T1024.b64.flash.full", "status": "invalid", "ts": 2,
         "result": {"valid": False, "steps_per_sec": None,
                    "seq_len": 1024, "attn": "flash", "batch": 64}},
    ]
    legs = longctx.assemble(records)
    assert len(legs) == 1
    assert legs[0]["status"] == "ok" and legs[0]["steps_per_sec"] == 45.0


def test_longctx_oom_becomes_leg_and_completeness_guard(longctx):
    records = [
        _rec("T1024.b64.flash.q", ts=1, steps_per_sec=45.0,
             seq_len=1024, attn="flash", batch=64),
        _rec("T1024.b64.full.q", ts=1, steps_per_sec=40.0,
             seq_len=1024, attn="full", batch=64),
        _rec("T16384.b16.full.q", status="oom", ts=2),
        _rec("T16384.b16.flash.q", ts=2, steps_per_sec=0.5,
             seq_len=16384, attn="flash", batch=16),
    ]
    legs = longctx.assemble(records)
    assert {(l["seq_len"], l["attn"], l["status"]) for l in legs} == {
        (1024, "flash", "ok"), (1024, "full", "ok"),
        (16384, "full", "oom"), (16384, "flash", "ok")}
    assert longctx.complete_enough(legs) == []
    # dropping the ceiling pair makes it unpublishable
    partial = [l for l in legs if l["seq_len"] == 1024]
    assert longctx.complete_enough(partial)


def test_longctx_full_leg_preferred_within_same_status(longctx):
    records = [
        _rec("T256.b64.full.q", ts=5, steps_per_sec=350.0,
             seq_len=256, attn="full", batch=64),
        _rec("T256.b64.full.full", ts=1, steps_per_sec=353.0,
             seq_len=256, attn="full", batch=64),
    ]
    legs = longctx.assemble(records)
    assert legs[0]["steps_per_sec"] == 353.0


def test_suspect_records_demoted_but_not_vanished(longctx, monkeypatch):
    """A quarantined record (SUSPECT registry: contradicted by stronger
    evidence, e.g. the 16x-slow dense T=1024 window read) loses to ANY
    clean record of the same shape — even a lower-priority quick one —
    but still publishes, carrying its note, when it is all there is."""
    ok = {"leg": "T64.b8.full.q", "status": "ok", "ts": 100,
          "result": {"model": "transformer", "attn": "full", "batch": 8,
                     "seq_len": 64, "steps_per_sec": 2.0, "valid": True}}
    monkeypatch.setattr(longctx, "SUSPECT",
                        {("T64.b8.full.q", 100): "contradicted"})
    legs = longctx.assemble([ok])
    assert legs[0]["suspect"] == "contradicted"   # alone: published+noted

    clean = {"leg": "T64.b8.full.q", "status": "ok", "ts": 50,
             "result": {"model": "transformer", "attn": "full", "batch": 8,
                        "seq_len": 64, "steps_per_sec": 40.0,
                        "valid": True}}
    legs = longctx.assemble([ok, clean])   # older clean record wins anyway
    assert legs[0]["steps_per_sec"] == 40.0
    assert "suspect" not in legs[0]

    # status stays primary: a gate-FAILED retry never displaces the
    # suspect gate-passing ok (information would be strictly lost)
    bad = {"leg": "T64.b8.full.q", "status": "invalid", "ts": 200,
           "result": {"model": "transformer", "attn": "full", "batch": 8,
                      "seq_len": 64, "steps_per_sec": 999.0,
                      "valid": False}}
    legs = longctx.assemble([ok, bad])
    assert legs[0]["status"] == "ok"
    assert legs[0]["suspect"] == "contradicted"

    # and a suspect pair never greenlights publication by itself
    flash_ok = {"leg": "T64.b8.flash.q", "status": "ok", "ts": 100,
                "result": {"model": "transformer", "attn": "flash",
                           "batch": 8, "seq_len": 64,
                           "steps_per_sec": 3.0, "valid": True}}
    oom_top = {"leg": "T128.b8.full.q", "status": "oom", "ts": 100}
    flash_top = {"leg": "T128.b8.flash.q", "status": "ok", "ts": 100,
                 "result": {"model": "transformer", "attn": "flash",
                            "batch": 8, "seq_len": 128,
                            "steps_per_sec": 1.0, "valid": True}}
    legs = longctx.assemble([ok, flash_ok, oom_top, flash_top])
    assert any("clean shared-T" in m for m in longctx.complete_enough(legs))
    legs = longctx.assemble([clean, flash_ok, oom_top, flash_top])
    assert longctx.complete_enough(legs) == []


def test_sweep_leg_at_default_edge_promoted(longctx, monkeypatch):
    """A sweep leg pinned at TODAY's default block edge is the same
    config a main flash leg would run now, so it qualifies as a flash
    candidate (this is how adopted-edge numbers publish without
    re-burning identical chip measurements); non-default edges stay
    sweep-artifact-only."""
    monkeypatch.setattr(longctx, "_default_block", lambda seq: 1024)
    main = _rec("T2048.b64.flash.q", ts=1, steps_per_sec=18.0,
                seq_len=2048, attn="flash", batch=64)
    at_default = _rec("sweep.T2048.b64.flash.blk1024", ts=2,
                      steps_per_sec=19.5, seq_len=2048, attn="flash",
                      batch=64)
    off_default = _rec("sweep.T2048.b64.flash.blk256", ts=3,
                       steps_per_sec=99.0, seq_len=2048, attn="flash",
                       batch=64)
    legs = longctx.assemble([main, at_default, off_default])
    assert len(legs) == 1
    # newer same-status default-edge sweep displaces the older main
    # leg; the blk-256 record (newest of all) never qualifies
    assert legs[0]["steps_per_sec"] == 19.5
    # a FULL main leg still outranks the quick sweep leg
    full = _rec("T2048.b64.flash.full", ts=0, steps_per_sec=18.5,
                seq_len=2048, attn="flash", batch=64)
    legs = longctx.assemble([main, at_default, full])
    assert legs[0]["steps_per_sec"] == 18.5


def test_sweep_promotion_follows_recorded_main_edge(longctx, monkeypatch):
    """When a main flash leg RECORDS the block it compiled with
    (flash_block in its result), sweep promotion keys on that runtime
    edge — `_resolve_block` can cap below `_pick_block`'s static default
    (one-pass-refused shapes), and promoting a sweep leg at the static
    default would then publish a config the main leg never ran. The
    static default stays the fallback for pre-field records."""
    monkeypatch.setattr(longctx, "_default_block", lambda seq: 1024)
    main = _rec("T2048.b64.flash.q", ts=1, steps_per_sec=18.0,
                seq_len=2048, attn="flash", batch=64, flash_block=512)
    at_recorded = _rec("sweep.T2048.b64.flash.blk512", ts=2,
                       steps_per_sec=19.5, seq_len=2048, attn="flash",
                       batch=64)
    at_static = _rec("sweep.T2048.b64.flash.blk1024", ts=3,
                     steps_per_sec=99.0, seq_len=2048, attn="flash",
                     batch=64)
    legs = longctx.assemble([main, at_recorded, at_static])
    assert len(legs) == 1
    # the recorded-edge sweep promotes; the static-default one (newest,
    # fastest) matches an edge the main leg never compiled and stays out
    assert legs[0]["steps_per_sec"] == 19.5

    # the newest ok main record defines the edge
    newer = _rec("T2048.b64.flash.full", ts=5, steps_per_sec=18.5,
                 seq_len=2048, attn="flash", batch=64, flash_block=1024)
    legs = longctx.assemble([main, newer, at_recorded, at_static])
    assert legs[0]["steps_per_sec"] == 18.5  # full main leg outranks all
    # ...and blk1024 now matches the recorded edge while blk512 does not
    blocks = longctx._recorded_blocks([main, newer])
    assert blocks == {(2048, 64): 1024}

    # an invalid/oom main record never defines the edge
    bad = {"leg": "T2048.b64.flash.q", "status": "invalid", "ts": 9,
           "result": {"valid": False, "flash_block": 256}}
    assert longctx._recorded_blocks([main, bad]) == {(2048, 64): 512}
