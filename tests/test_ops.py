"""Pallas kernel layer (split_learning_tpu.ops) — numerics vs references.

Kernels run in Mosaic interpreter mode on the CPU test mesh
(SURVEY.md §4 item 4); the same code compiles on real TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from split_learning_tpu.ops import (
    fused_cross_entropy,
    quantize_dequantize,
    quantize_int8,
    dequantize_int8,
    reference_cross_entropy,
)
from split_learning_tpu.ops.sgd import fused_sgd_step, init_trace, reference_sgd_step
from split_learning_tpu.transport import codec


# --------------------------------------------------------------------- #
# fused cross-entropy
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("b,c", [(64, 10), (7, 10), (8, 128), (33, 200)])
def test_ce_forward_matches_reference(rng, b, c):
    kx, ky = jax.random.split(rng)
    logits = jax.random.normal(kx, (b, c), jnp.float32) * 3.0
    labels = jax.random.randint(ky, (b,), 0, c)
    got = fused_cross_entropy(logits, labels)
    want = reference_cross_entropy(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("b,c", [(64, 10), (7, 13)])
def test_ce_gradient_matches_reference(rng, b, c):
    kx, ky = jax.random.split(rng)
    logits = jax.random.normal(kx, (b, c), jnp.float32) * 2.0
    labels = jax.random.randint(ky, (b,), 0, c)
    g_got = jax.grad(fused_cross_entropy)(logits, labels)
    g_want = jax.grad(reference_cross_entropy)(logits, labels)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_want),
                               rtol=1e-5, atol=1e-6)


def test_ce_inside_jit_value_and_grad(rng):
    """The kernel must trace under jit (the fused-trainer context)."""
    kx, ky = jax.random.split(rng)
    logits = jax.random.normal(kx, (16, 10), jnp.float32)
    labels = jax.random.randint(ky, (16,), 0, 10)

    @jax.jit
    def f(lg, lb):
        return jax.value_and_grad(fused_cross_entropy)(lg, lb)

    loss, grad = f(logits, labels)
    want = reference_cross_entropy(logits, labels)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(want), rtol=1e-5)
    assert grad.shape == logits.shape


# --------------------------------------------------------------------- #
# fused SGD
# --------------------------------------------------------------------- #
def _tree(rng):
    k1, k2, k3 = jax.random.split(rng, 3)
    return {
        "conv": {"kernel": jax.random.normal(k1, (3, 3, 1, 32)),
                 "bias": jax.random.normal(k2, (32,))},
        "dense": jax.random.normal(k3, (129, 257)),  # non-lane-aligned
    }


def test_sgd_no_momentum_matches_reference(rng):
    kp, kg = jax.random.split(rng)
    params, grads = _tree(kp), _tree(kg)
    got, trace = fused_sgd_step(params, grads, None, lr=0.01)
    want, _ = reference_sgd_step(params, grads, None, lr=0.01)
    assert trace is None
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-5, atol=1e-7),
        got, want)


def test_sgd_momentum_matches_optax_over_steps(rng):
    """Multi-step: the fused trace must evolve exactly like optax.sgd."""
    import optax
    kp, kg = jax.random.split(rng)
    params = _tree(kp)
    tx = optax.sgd(0.01, momentum=0.9)
    opt_state = tx.init(params)
    fused_params, trace = params, init_trace(params)

    for i in range(3):
        grads = _tree(jax.random.fold_in(kg, i))
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        fused_params, trace = fused_sgd_step(
            fused_params, grads, trace, lr=0.01, momentum=0.9)

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-5, atol=1e-7),
        fused_params, params)


def test_sgd_large_leaf_gridded(rng):
    """A leaf bigger than one block exercises the 1-D grid path."""
    p = jax.random.normal(rng, (1200, 300))  # 360k elems > 512*128
    g = jnp.ones_like(p)
    got, _ = fused_sgd_step(p, g, None, lr=0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(p) - 0.5,
                               rtol=1e-6)


# --------------------------------------------------------------------- #
# int8 quantization
# --------------------------------------------------------------------- #
def test_quantize_roundtrip_error_bound(rng):
    x = jax.random.normal(rng, (64, 26, 26, 32), jnp.float32)
    x_rt = quantize_dequantize(x)
    # max error of symmetric int8 is scale/2 = max|x| / 254
    bound = float(jnp.max(jnp.abs(x))) / 254.0 + 1e-6
    assert float(jnp.max(jnp.abs(x_rt - x))) <= bound


def test_quantize_zero_tensor(rng):
    x = jnp.zeros((8, 128), jnp.float32)
    x_rt = quantize_dequantize(x)
    np.testing.assert_array_equal(np.asarray(x_rt), 0.0)


def test_quantize_kernel_matches_wire_codec(rng):
    """The Pallas kernel and the numpy wire codec share one math."""
    x = jax.random.normal(rng, (16, 26, 26, 32), jnp.float32)
    q_kernel, scale_kernel = quantize_int8(x)
    wire = codec.q8_compress(np.asarray(x))
    np.testing.assert_allclose(float(scale_kernel), wire["scale"], rtol=1e-6)
    got = dequantize_int8(q_kernel, scale_kernel, x.shape)
    want = codec.q8_decompress(wire)
    np.testing.assert_allclose(np.asarray(got), want, atol=float(scale_kernel))


def test_q8_wire_shrinks_payload(rng):
    x = np.asarray(jax.random.normal(rng, (64, 26, 26, 32), jnp.float32))
    raw = codec.encode(x)
    compressed = codec.encode(codec.q8_compress(x))
    assert len(compressed) < len(raw) / 3.5  # ~4x minus header overhead
    back = codec.decompress_tree(codec.decode(compressed))
    assert back.shape == x.shape and back.dtype == x.dtype


# --------------------------------------------------------------------- #
# fused trainer on the pallas path
# --------------------------------------------------------------------- #
def test_fused_trainer_pallas_matches_xla(rng, mnist_batch):
    from split_learning_tpu.models import get_plan
    from split_learning_tpu.runtime.fused import FusedSplitTrainer
    from split_learning_tpu.utils import Config

    x, y = mnist_batch
    plan = get_plan(mode="split")
    t_xla = FusedSplitTrainer(plan, Config(mode="split"), rng, np.asarray(x))
    t_pal = FusedSplitTrainer(plan, Config(mode="split", kernels="pallas"),
                              rng, np.asarray(x))
    for _ in range(2):
        l_xla = t_xla.train_step(np.asarray(x), np.asarray(y))
        l_pal = t_pal.train_step(np.asarray(x), np.asarray(y))
    np.testing.assert_allclose(l_pal, l_xla, rtol=1e-4, atol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        t_pal.params, t_xla.params)


def test_http_transport_int8_compression(rng, mnist_batch):
    """End-to-end split step over HTTP with int8 wire compression."""
    from split_learning_tpu.models import get_plan
    from split_learning_tpu.runtime import ServerRuntime, SplitClientTrainer
    from split_learning_tpu.transport.http import HttpTransport, SplitHTTPServer
    from split_learning_tpu.utils import Config

    x, y = mnist_batch
    x, y = np.asarray(x[:16]), np.asarray(y[:16])
    cfg = Config(mode="split", batch_size=16)
    plan = get_plan(mode="split")
    runtime = ServerRuntime(plan, cfg, rng, x)
    server = SplitHTTPServer(runtime).start()
    try:
        plain = HttpTransport(server.url)
        lossy = HttpTransport(server.url, compress="int8")
        c = SplitClientTrainer(plan, cfg, rng, lossy)
        loss = c.train_step(x, y, 0)
        assert np.isfinite(loss)
        # cut tensor is [16, 26, 26, 32]; int8 wire ~1 byte/elem vs 4 fp32
        acts_elems = 16 * 26 * 26 * 32
        assert lossy.stats.bytes_sent < acts_elems * 1.1
        assert lossy.stats.bytes_received < acts_elems * 1.1
        plain.close()
        lossy.close()
    finally:
        server.stop()


# --------------------------------------------------------------------- #
# gridded large-payload paths (round-1 VERDICT weak #8)
# --------------------------------------------------------------------- #
def test_quantize_resnet_sized_activation_gridded(rng):
    """A ResNet stage output ([256, 16, 16, 64] = 4M elements, 32k rows)
    must take the row-block grid path and round-trip within the int8
    error bound, one VMEM block at a time."""
    from split_learning_tpu.ops.quantize import _BLOCK_ROWS, _to_tiles
    x = jax.random.normal(rng, (256, 16, 16, 64), jnp.float32) * 2.0
    rows = _to_tiles(x)[0].shape[0]
    assert rows > _BLOCK_ROWS  # this size exercises the grid, not the
    # single-block fast path
    q, scale = quantize_int8(x)
    assert q.shape[0] == rows and q.dtype == jnp.int8
    back = dequantize_int8(q, scale, x.shape, x.dtype)
    amax = float(jnp.max(jnp.abs(x)))
    np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                               atol=amax / 127.0 + 1e-6)
    # the global scale must match the un-tiled definition exactly
    np.testing.assert_allclose(float(scale), amax / 127.0, rtol=1e-6)


def test_quantize_grid_matches_single_block_semantics(rng):
    """Grid path and fast path implement the same function: compare a
    size just over the block boundary against the jnp definition."""
    from split_learning_tpu.ops.quantize import _BLOCK_ROWS, LANE
    n = (_BLOCK_ROWS + 8) * LANE  # 1 block + a bit -> grid path
    x = jax.random.normal(rng, (n,), jnp.float32)
    q, scale = quantize_int8(x)
    want_scale = max(float(jnp.max(jnp.abs(x))) / 127.0, 1e-12)
    np.testing.assert_allclose(float(scale), want_scale, rtol=1e-6)
    want_q = np.clip(np.round(np.asarray(x) / want_scale), -127, 127)
    np.testing.assert_array_equal(
        np.asarray(q).reshape(-1)[:n], want_q.astype(np.int8))


def test_ce_large_batch_gridded(rng):
    """B=4096 > _BLOCK_B exercises the row-block CE grid; forward and
    gradient must match the reference exactly as in the small case."""
    from split_learning_tpu.ops.cross_entropy import _BLOCK_B
    b, c = 4096, 10
    assert b > _BLOCK_B
    kx, ky = jax.random.split(rng)
    logits = jax.random.normal(kx, (b, c), jnp.float32) * 3.0
    labels = jax.random.randint(ky, (b,), 0, c)
    got = fused_cross_entropy(logits, labels)
    want = reference_cross_entropy(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
    g_got = jax.grad(lambda l: fused_cross_entropy(l, labels))(logits)
    g_want = jax.grad(lambda l: reference_cross_entropy(l, labels))(logits)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_want),
                               rtol=1e-4, atol=1e-6)


def test_ce_non_multiple_large_batch_gridded(rng):
    """Last-block row masking: B not a multiple of the block size."""
    b, c = 1500, 17
    kx, ky = jax.random.split(rng)
    logits = jax.random.normal(kx, (b, c), jnp.float32)
    labels = jax.random.randint(ky, (b,), 0, c)
    got = fused_cross_entropy(logits, labels)
    want = reference_cross_entropy(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
