"""utils.backend.ensure_pinned_platform_hermetic — the guard that keeps
CPU-pinned entry points from dialing a wedged device-plugin tunnel
(tests/conftest.py has the same guard inline; the CLI and scripts use
this one). The subtle contract: JAX_PLATFORMS is a *priority list*, so
the guard must preserve its order when it rewrites the config."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code: str, **env):
    full_env = dict(os.environ)
    full_env.update(env)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, env=full_env, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout


def test_preserves_platform_priority_order():
    # "cpu" first must stay first — an alphabetical sort would also pass
    # here, so use a pair where sorted order differs from given order
    out = _run(
        "from split_learning_tpu.utils import "
        "ensure_pinned_platform_hermetic as e\n"
        "e()\n"
        "import jax\n"
        "print(jax.config.jax_platforms)\n",
        JAX_PLATFORMS="cpu,axon")
    assert out.strip().splitlines()[-1] == "cpu,axon"


def test_idempotent_and_noop_without_pin():
    out = _run(
        "import os\n"
        "os.environ.pop('JAX_PLATFORMS', None)\n"
        "from split_learning_tpu.utils import "
        "ensure_pinned_platform_hermetic as e\n"
        "e(); e()\n"
        "print('OK')\n",
        JAX_PLATFORMS="")
    assert out.strip().endswith("OK")


def test_drops_out_of_set_plugin_factory():
    out = _run(
        "from split_learning_tpu.utils import "
        "ensure_pinned_platform_hermetic as e\n"
        "e()\n"
        "import jax\n"
        "import jax._src.xla_bridge as xb\n"
        "print('axon' in xb._backend_factories)\n"
        "print(sorted({d.platform for d in jax.devices()}))\n",
        JAX_PLATFORMS="cpu")
    lines = out.strip().splitlines()
    assert lines[-2] == "False"
    assert lines[-1] == "['cpu']"
