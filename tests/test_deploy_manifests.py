"""Structural validation of deploy/ manifests (round-1 VERDICT next #7).

No cluster and no kubeconform in the hermetic environment, so this is a
schema-shaped lint over the parsed YAML: the invariants that have actually
bitten (cross-namespace secret refs, selector/label drift, dead probes,
floating image tags, DNS names pointing at services that don't exist) are
asserted directly. Reference analog: the manifests these mirror are
`/root/reference/k8s/mlflow-stack.yaml` and `k8s/split-learning.yaml`.
"""

import os
import re

import pytest
import yaml

DEPLOY = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "deploy")
MANIFESTS = ["mlflow-stack.yaml", "split-learning.yaml"]


def _docs():
    out = []
    for name in MANIFESTS:
        with open(os.path.join(DEPLOY, name)) as f:
            for doc in yaml.safe_load_all(f):
                if doc:
                    out.append((name, doc))
    return out


DOCS = _docs()


def _by_kind(kind):
    return [(n, d) for n, d in DOCS if d.get("kind") == kind]


def _pod_spec(doc):
    return doc["spec"]["template"]["spec"]


def _containers(doc):
    spec = _pod_spec(doc)
    return spec.get("initContainers", []) + spec["containers"]


def test_every_doc_has_identity():
    assert len(DOCS) >= 10
    for name, doc in DOCS:
        assert doc.get("apiVersion"), (name, doc)
        assert doc.get("kind"), (name, doc)
        assert doc.get("metadata", {}).get("name"), (name, doc)


def test_workloads_pin_image_tags():
    for name, doc in _by_kind("Deployment") + _by_kind("StatefulSet") + \
            _by_kind("Job"):
        for c in _containers(doc):
            image = c["image"]
            if c.get("imagePullPolicy") == "Never" or \
                    _pod_spec(doc).get("imagePullPolicy") == "Never":
                continue  # locally-built image, tag is meaningless
            if image.startswith("split-learning-tpu:"):
                continue  # the repo's own image, built+imported locally
            assert ":" in image and not image.endswith(":latest"), (
                f"{name}: {doc['metadata']['name']} container {c['name']} "
                f"uses a floating tag: {image}")


def test_deployments_and_statefulsets_have_readiness_probes():
    for name, doc in _by_kind("Deployment") + _by_kind("StatefulSet"):
        assert any("readinessProbe" in c for c in _containers(doc)), (
            f"{name}: {doc['metadata']['name']} has no readiness probe "
            f"(the reference's in-cluster /health was dead code — "
            f"SURVEY.md §4)")


def test_service_selectors_match_pod_labels():
    workloads = _by_kind("Deployment") + _by_kind("StatefulSet")
    for name, svc in _by_kind("Service"):
        sel = svc["spec"].get("selector")
        if not sel:
            continue
        ns = svc["metadata"].get("namespace")
        matched = False
        for _, w in workloads:
            if w["metadata"].get("namespace") != ns:
                continue
            labels = w["spec"]["template"]["metadata"].get("labels", {})
            if all(labels.get(k) == v for k, v in sel.items()):
                matched = True
        assert matched, (
            f"{name}: Service {svc['metadata']['name']} selector {sel} "
            f"matches no workload pod labels in namespace {ns}")


def _secrets_by_ns():
    out = {}
    for _, doc in _by_kind("Secret"):
        ns = doc["metadata"].get("namespace")
        keys = set(doc.get("stringData", {})) | set(doc.get("data", {}))
        out.setdefault(ns, {})[doc["metadata"]["name"]] = keys
    return out


def test_secret_refs_resolve_within_their_namespace():
    """secretKeyRef is namespace-local — the class of bug where a pod
    references a Secret that only exists in another namespace."""
    secrets = _secrets_by_ns()
    for name, doc in _by_kind("Deployment") + _by_kind("StatefulSet") + \
            _by_kind("Job"):
        ns = doc["metadata"].get("namespace")
        for c in _containers(doc):
            for env in c.get("env", []):
                ref = env.get("valueFrom", {}).get("secretKeyRef")
                if not ref:
                    continue
                if ref.get("optional"):
                    continue
                have = secrets.get(ns, {})
                assert ref["name"] in have, (
                    f"{name}: {doc['metadata']['name']} env {env['name']} "
                    f"references Secret {ref['name']} which does not exist "
                    f"in namespace {ns}")
                assert ref["key"] in have[ref["name"]], (
                    f"{name}: Secret {ref['name']} has no key {ref['key']}")


def test_cluster_dns_names_point_at_defined_services():
    """Every *.svc.cluster.local URL in env values must resolve to a
    Service defined in these manifests (name + namespace + port)."""
    services = {}
    for _, svc in _by_kind("Service"):
        key = (svc["metadata"]["name"], svc["metadata"].get("namespace"))
        services[key] = {p["port"] for p in svc["spec"]["ports"]}
    pat = re.compile(
        r"https?://([a-z0-9-]+)\.([a-z0-9-]+)\.svc\.cluster\.local:(\d+)")
    found = 0
    for name, doc in DOCS:
        for m in pat.finditer(yaml.safe_dump(doc)):
            svc_name, ns, port = m.group(1), m.group(2), int(m.group(3))
            found += 1
            assert (svc_name, ns) in services, (
                f"{name}: URL references undefined Service "
                f"{svc_name}.{ns}: {m.group(0)}")
            assert port in services[(svc_name, ns)], (
                f"{name}: Service {svc_name}.{ns} does not expose "
                f"port {port}")
    assert found >= 2  # minio endpoint(s) + mlflow tracking URI


def test_s3_stack_is_deployable():
    """The round-1 gap: S3Store and the MLflow artifact root had no
    in-cluster backing. Pin the pieces: a MinIO StatefulSet, a bucket-init
    Job creating mlops-bucket, and MLflow pointed at s3://mlops-bucket."""
    kinds = {(d["kind"], d["metadata"]["name"]) for _, d in DOCS}
    assert ("StatefulSet", "minio") in kinds
    assert ("Job", "bucket-init") in kinds
    [(_, mlflow)] = [(n, d) for n, d in _by_kind("Deployment")
                     if d["metadata"]["name"] == "mlflow"]
    blob = yaml.safe_dump(mlflow)
    assert "s3://mlops-bucket" in blob  # ≡ reference artifact root
    assert "MLFLOW_S3_ENDPOINT_URL" in blob
    [(_, job)] = [(n, d) for n, d in _by_kind("Job")
                  if d["metadata"]["name"] == "bucket-init"]
    assert "mlops-bucket" in yaml.safe_dump(job)


def test_store_from_config_uses_the_same_env_surface():
    """The client pod env (S3_ENDPOINT_URL/AWS_*) must map onto
    Config.s3_* and activate S3Store; without the endpoint the loader
    stays local. boto3 is absent in the test image, so activation is
    observed as S3Store's ImportError rather than a live client."""
    from split_learning_tpu.data import store_from_config
    from split_learning_tpu.utils import Config

    assert store_from_config(Config()) is None
    cfg = Config(s3_endpoint="http://minio.mlflow.svc.cluster.local:9000",
                 s3_access_key="a", s3_secret_key="b")
    try:
        store = store_from_config(cfg)
    except ImportError as e:
        assert "boto3" in str(e)
    else:  # boto3 present: it must be a real S3Store on that endpoint
        from split_learning_tpu.data import S3Store
        assert isinstance(store, S3Store)


def test_probe_ports_match_container_ports():
    """kubeconform-style check (round-3 VERDICT next #8): every
    liveness/readiness/startup probe must target a port the same
    container actually declares — a probe on a dead port passes schema
    validation and then CrashLoops in-cluster."""
    checked = 0
    for name, doc in _by_kind("Deployment") + _by_kind("StatefulSet"):
        for c in _pod_spec(doc)["containers"]:
            declared = set()
            for p in c.get("ports", []):
                declared.add(p["containerPort"])
                if "name" in p:
                    declared.add(p["name"])
            for kind in ("livenessProbe", "readinessProbe", "startupProbe"):
                probe = c.get(kind)
                if not probe:
                    continue
                target = (probe.get("httpGet") or probe.get("tcpSocket")
                          or {}).get("port")
                if target is None:
                    continue  # exec probe: no port to check
                checked += 1
                assert target in declared, (
                    f"{name}: {doc['metadata']['name']}/{c['name']} "
                    f"{kind} targets port {target!r} but the container "
                    f"declares {sorted(map(str, declared))}")
    assert checked >= 3


def test_pod_env_names_are_consumed_by_config():
    """Every app-config env var the training pods set (including the
    commented-out S3 block, which users are told to uncomment) must be a
    name Config.from_env actually reads — a typo'd SLT_* var silently
    configures nothing."""
    from split_learning_tpu.utils.config import _ENV_MAP

    consumed = set(_ENV_MAP.values())
    # read by the MLflow client library, not by Config
    library_env = {"MLFLOW_S3_ENDPOINT_URL"}
    path = os.path.join(DEPLOY, "split-learning.yaml")
    with open(path) as f:
        text = f.read()
    # commented env entries are part of the documented surface too
    names = set(re.findall(
        r"^\s*#?\s*- name:\s*([A-Z][A-Z0-9_]+)\s*$", text, re.M))
    app_names = {n for n in names
                 if n.startswith("SLT_") or n in ("LEARNING_MODE",
                                                  "MLFLOW_TRACKING_URI",
                                                  "S3_ENDPOINT_URL",
                                                  "AWS_ACCESS_KEY_ID",
                                                  "AWS_SECRET_ACCESS_KEY")
                 or n in library_env}
    assert len(app_names) >= 5
    for n in app_names - library_env:
        assert n in consumed, (
            f"split-learning.yaml sets env {n} which Config.from_env "
            f"never reads (known names: {sorted(consumed)})")


def test_pvc_references_resolve():
    """Every persistentVolumeClaim.claimName in a pod spec must resolve
    to a PVC document or a StatefulSet volumeClaimTemplate in the same
    namespace."""
    defined = set()
    for _, d in DOCS:
        if d.get("kind") == "PersistentVolumeClaim":
            defined.add((d["metadata"].get("namespace"),
                         d["metadata"]["name"]))
    for _, d in _by_kind("StatefulSet"):
        ns = d["metadata"].get("namespace")
        for tmpl in d["spec"].get("volumeClaimTemplates", []):
            # pods see <template-name>-<sts-name>-<ordinal>; record the
            # template prefix for the sts's own volumes
            defined.add((ns, tmpl["metadata"]["name"]))
    checked = 0
    for name, doc in _by_kind("Deployment") + _by_kind("StatefulSet") + \
            _by_kind("Job"):
        ns = doc["metadata"].get("namespace")
        for vol in _pod_spec(doc).get("volumes", []):
            claim = vol.get("persistentVolumeClaim", {}).get("claimName")
            if claim:
                checked += 1
                assert (ns, claim) in defined, (
                    f"{name}: {doc['metadata']['name']} mounts PVC "
                    f"{claim} which is not defined in namespace {ns}")
    assert checked >= 1
