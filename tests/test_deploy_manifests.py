"""Structural validation of deploy/ manifests (round-1 VERDICT next #7).

No cluster and no kubeconform in the hermetic environment, so this is a
schema-shaped lint over the parsed YAML: the invariants that have actually
bitten (cross-namespace secret refs, selector/label drift, dead probes,
floating image tags, DNS names pointing at services that don't exist) are
asserted directly. Reference analog: the manifests these mirror are
`/root/reference/k8s/mlflow-stack.yaml` and `k8s/split-learning.yaml`.
"""

import os
import re

import pytest
import yaml

DEPLOY = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "deploy")
MANIFESTS = ["mlflow-stack.yaml", "split-learning.yaml"]


def _docs():
    out = []
    for name in MANIFESTS:
        with open(os.path.join(DEPLOY, name)) as f:
            for doc in yaml.safe_load_all(f):
                if doc:
                    out.append((name, doc))
    return out


DOCS = _docs()


def _by_kind(kind):
    return [(n, d) for n, d in DOCS if d.get("kind") == kind]


def _pod_spec(doc):
    return doc["spec"]["template"]["spec"]


def _containers(doc):
    spec = _pod_spec(doc)
    return spec.get("initContainers", []) + spec["containers"]


def test_every_doc_has_identity():
    assert len(DOCS) >= 10
    for name, doc in DOCS:
        assert doc.get("apiVersion"), (name, doc)
        assert doc.get("kind"), (name, doc)
        assert doc.get("metadata", {}).get("name"), (name, doc)


def test_workloads_pin_image_tags():
    for name, doc in _by_kind("Deployment") + _by_kind("StatefulSet") + \
            _by_kind("Job"):
        for c in _containers(doc):
            image = c["image"]
            if c.get("imagePullPolicy") == "Never" or \
                    _pod_spec(doc).get("imagePullPolicy") == "Never":
                continue  # locally-built image, tag is meaningless
            if image.startswith("split-learning-tpu:"):
                continue  # the repo's own image, built+imported locally
            assert ":" in image and not image.endswith(":latest"), (
                f"{name}: {doc['metadata']['name']} container {c['name']} "
                f"uses a floating tag: {image}")


def test_deployments_and_statefulsets_have_readiness_probes():
    for name, doc in _by_kind("Deployment") + _by_kind("StatefulSet"):
        assert any("readinessProbe" in c for c in _containers(doc)), (
            f"{name}: {doc['metadata']['name']} has no readiness probe "
            f"(the reference's in-cluster /health was dead code — "
            f"SURVEY.md §4)")


def test_service_selectors_match_pod_labels():
    workloads = _by_kind("Deployment") + _by_kind("StatefulSet")
    for name, svc in _by_kind("Service"):
        sel = svc["spec"].get("selector")
        if not sel:
            continue
        ns = svc["metadata"].get("namespace")
        matched = False
        for _, w in workloads:
            if w["metadata"].get("namespace") != ns:
                continue
            labels = w["spec"]["template"]["metadata"].get("labels", {})
            if all(labels.get(k) == v for k, v in sel.items()):
                matched = True
        assert matched, (
            f"{name}: Service {svc['metadata']['name']} selector {sel} "
            f"matches no workload pod labels in namespace {ns}")


def _secrets_by_ns():
    out = {}
    for _, doc in _by_kind("Secret"):
        ns = doc["metadata"].get("namespace")
        keys = set(doc.get("stringData", {})) | set(doc.get("data", {}))
        out.setdefault(ns, {})[doc["metadata"]["name"]] = keys
    return out


def test_secret_refs_resolve_within_their_namespace():
    """secretKeyRef is namespace-local — the class of bug where a pod
    references a Secret that only exists in another namespace."""
    secrets = _secrets_by_ns()
    for name, doc in _by_kind("Deployment") + _by_kind("StatefulSet") + \
            _by_kind("Job"):
        ns = doc["metadata"].get("namespace")
        for c in _containers(doc):
            for env in c.get("env", []):
                ref = env.get("valueFrom", {}).get("secretKeyRef")
                if not ref:
                    continue
                if ref.get("optional"):
                    continue
                have = secrets.get(ns, {})
                assert ref["name"] in have, (
                    f"{name}: {doc['metadata']['name']} env {env['name']} "
                    f"references Secret {ref['name']} which does not exist "
                    f"in namespace {ns}")
                assert ref["key"] in have[ref["name"]], (
                    f"{name}: Secret {ref['name']} has no key {ref['key']}")


def test_cluster_dns_names_point_at_defined_services():
    """Every *.svc.cluster.local URL in env values must resolve to a
    Service defined in these manifests (name + namespace + port)."""
    services = {}
    for _, svc in _by_kind("Service"):
        key = (svc["metadata"]["name"], svc["metadata"].get("namespace"))
        services[key] = {p["port"] for p in svc["spec"]["ports"]}
    pat = re.compile(
        r"https?://([a-z0-9-]+)\.([a-z0-9-]+)\.svc\.cluster\.local:(\d+)")
    found = 0
    for name, doc in DOCS:
        for m in pat.finditer(yaml.safe_dump(doc)):
            svc_name, ns, port = m.group(1), m.group(2), int(m.group(3))
            found += 1
            assert (svc_name, ns) in services, (
                f"{name}: URL references undefined Service "
                f"{svc_name}.{ns}: {m.group(0)}")
            assert port in services[(svc_name, ns)], (
                f"{name}: Service {svc_name}.{ns} does not expose "
                f"port {port}")
    assert found >= 2  # minio endpoint(s) + mlflow tracking URI


def test_s3_stack_is_deployable():
    """The round-1 gap: S3Store and the MLflow artifact root had no
    in-cluster backing. Pin the pieces: a MinIO StatefulSet, a bucket-init
    Job creating mlops-bucket, and MLflow pointed at s3://mlops-bucket."""
    kinds = {(d["kind"], d["metadata"]["name"]) for _, d in DOCS}
    assert ("StatefulSet", "minio") in kinds
    assert ("Job", "bucket-init") in kinds
    [(_, mlflow)] = [(n, d) for n, d in _by_kind("Deployment")
                     if d["metadata"]["name"] == "mlflow"]
    blob = yaml.safe_dump(mlflow)
    assert "s3://mlops-bucket" in blob  # ≡ reference artifact root
    assert "MLFLOW_S3_ENDPOINT_URL" in blob
    [(_, job)] = [(n, d) for n, d in _by_kind("Job")
                  if d["metadata"]["name"] == "bucket-init"]
    assert "mlops-bucket" in yaml.safe_dump(job)


def test_store_from_config_uses_the_same_env_surface():
    """The client pod env (S3_ENDPOINT_URL/AWS_*) must map onto
    Config.s3_* and activate S3Store; without the endpoint the loader
    stays local. boto3 is absent in the test image, so activation is
    observed as S3Store's ImportError rather than a live client."""
    from split_learning_tpu.data import store_from_config
    from split_learning_tpu.utils import Config

    assert store_from_config(Config()) is None
    cfg = Config(s3_endpoint="http://minio.mlflow.svc.cluster.local:9000",
                 s3_access_key="a", s3_secret_key="b")
    try:
        store = store_from_config(cfg)
    except ImportError as e:
        assert "boto3" in str(e)
    else:  # boto3 present: it must be a real S3Store on that endpoint
        from split_learning_tpu.data import S3Store
        assert isinstance(store, S3Store)
