"""Elastic recovery: a client outliving a server crash.

The reference's failure story is "drop the batch and keep going"
(``src/client_part.py:127-129``) plus k8s restart semantics that silently
desync the halves (SURVEY.md §3.4/§5 "Failure detection"). Here the full
recovery cycle is exercised end-to-end over a real socket: the server dies
mid-training, a replacement resumes from its checkpoint and re-arms the
step handshake, and the client's bounded exponential-backoff retry outwaits
the outage — no batch lost, no desync.
"""

import socket
import threading
import time

import pytest

import jax
import numpy as np

from split_learning_tpu.models import get_plan
from split_learning_tpu.runtime import ServerRuntime, SplitClientTrainer
from split_learning_tpu.runtime.checkpoint import Checkpointer
from split_learning_tpu.runtime.client import FailurePolicy
from split_learning_tpu.transport.http import HttpTransport, SplitHTTPServer
from split_learning_tpu.utils import Config

BATCH = 8


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_client_survives_server_restart(tmp_path):
    cfg = Config(mode="split", batch_size=BATCH)
    plan = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    port = _free_port()
    ckptr = Checkpointer(str(tmp_path / "srv"))

    runtime1 = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), sample)
    runtime1.on_step = lambda s: ckptr.save(s + 1, {"server": runtime1.state})
    server1 = SplitHTTPServer(runtime1, port=port).start()

    transport = HttpTransport(f"http://127.0.0.1:{port}")
    client = SplitClientTrainer(
        plan, cfg, jax.random.PRNGKey(0), transport,
        failure_policy=FailurePolicy.RETRY, max_retries=8,
        retry_backoff=0.2)

    rs = np.random.RandomState(0)
    data = [(rs.randn(BATCH, 28, 28, 1).astype(np.float32),
             rs.randint(0, 10, (BATCH,)).astype(np.int64))
            for _ in range(10)]

    losses = [client.train_step(x, y, s)
              for s, (x, y) in enumerate(data[:5])]
    assert all(np.isfinite(l) for l in losses)

    # ---- crash ----
    server1.stop()
    replacement = {}

    def revive():
        time.sleep(0.7)  # a real outage, longer than the first backoff
        runtime2 = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), sample)
        latest = ckptr.latest_step()
        tree = ckptr.restore({"server": runtime2.state})
        runtime2.resume_from(tree["server"], latest)
        replacement["runtime"] = runtime2
        replacement["server"] = SplitHTTPServer(runtime2, port=port).start()

    reviver = threading.Thread(target=revive)
    reviver.start()
    try:
        # steps 5..9 ride through the outage on retry+backoff
        more = [client.train_step(x, y, 5 + i)
                for i, (x, y) in enumerate(data[5:])]
        assert all(np.isfinite(l) for l in more)
        assert client.dropped_batches == 0
        # the replacement acknowledged every post-crash step: no desync
        assert replacement["runtime"]._last_step == {0: 9}
    finally:
        reviver.join()
        transport.close()
        replacement["server"].stop()
