"""Decoupled backward / 2BP (PR 10): the reply path returns the
cut-layer gradient immediately while the server weight update drains
off the critical path, batched up to ``apply_lag``.

Pins, in order: lag=0 is bit-identical to the legacy fused program;
``--decouple-bwd`` off leaves the PR 9 tree untouched (no decoupled
programs, no new spans, no new counters); the queue depth never exceeds
``apply_lag`` and every flush barrier catches the state up; a replayed
duplicate never re-enqueues an apply; a coalesced group's replies land
before its (still queued) weight update; a checkpoint taken mid-lag
round-trips to the same continuation trajectory; and both new jitted
programs are recompile-free at steady state."""

import jax
import numpy as np

from split_learning_tpu import obs
from split_learning_tpu.models import get_plan
from split_learning_tpu.obs import dispatch_debug
from split_learning_tpu.runtime import ServerRuntime, SplitClientTrainer
from split_learning_tpu.runtime.multi_client import MultiClientSplitRunner
from split_learning_tpu.transport.local import LocalTransport
from split_learning_tpu.utils import Config

BATCH = 4


def _server(**kw):
    cfg = Config(mode="split", batch_size=BATCH, num_clients=2)
    plan = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    return cfg, plan, ServerRuntime(plan, cfg, jax.random.PRNGKey(2),
                                    sample, **kw)


def _batch(seed=0):
    rs = np.random.RandomState(seed)
    return (rs.randn(BATCH, 28, 28, 1).astype(np.float32),
            rs.randint(0, 10, BATCH).astype(np.int64))


def _series(steps=5, **kw):
    cfg, plan, server = _server(**kw)
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server))
    try:
        return [client.train_step(*_batch(i), i) for i in range(steps)], \
            server
    finally:
        server.close()


# ---------------------------------------------------------------------- #
# numerics: lag=0 bit-identity, default-off pin
# ---------------------------------------------------------------------- #

def test_lag0_bit_identical_to_legacy():
    """Splitting the fused value_and_grad into reply + immediate apply
    cannot change numerics: with apply_lag=0 the update still lands
    inside the same lock-held window, in the same order, from the same
    params — the loss series must match bit for bit."""
    legacy, _ = _series()
    lag0, srv0 = _series(decouple_bwd=True, apply_lag=0)
    assert legacy == lag0
    # and the replies really went through the decoupled machinery
    dec = srv0.health()["decoupled_bwd"]
    assert dec["deferred_enqueued"] == 5
    assert dec["deferred_applied"] == 5
    assert dec["deferred_apply_depth"] == 0


def test_default_off_is_the_untouched_legacy_path():
    """--decouple-bwd off must leave the PR 9 tree bit-for-bit alone:
    no decoupled programs compiled, no deferred queue, no reply_grad /
    deferred_apply spans traced, no deferred counters exported."""
    cfg, plan, server = _server()
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server))
    try:
        assert server.decouple_bwd is False
        assert server._deferred is None
        assert not hasattr(server, "_reply_step")
        assert not hasattr(server, "_deferred_apply")
        client.train_step(*_batch(0), 0)
        tr = obs.enable()
        try:
            client.train_step(*_batch(1), 1)
        finally:
            obs.disable()
        names = {s["name"] for s in tr.spans()}
        assert "reply_grad" not in names
        assert "deferred_apply" not in names
        snap = server.metrics()
        assert "decoupled_bwd" not in server.health()
        assert not any(k.startswith("deferred_") for k in snap["counters"])
        assert server.flush_deferred() == 0  # barrier no-ops when coupled
    finally:
        server.close()


def test_ctor_validation():
    import pytest
    with pytest.raises(ValueError, match="apply_lag"):
        _server(decouple_bwd=True, apply_lag=-1)
    with pytest.raises(ValueError, match="decouple_bwd"):
        _server(apply_lag=2)


# ---------------------------------------------------------------------- #
# staleness bound + flush barriers
# ---------------------------------------------------------------------- #

def test_lag_bounds_queue_depth_and_flush_catches_up():
    """The staleness invariant: after every reply the queue holds at
    most apply_lag updates (step t forwards with weights from t-k,
    k <= lag), and export_state drains everything before handing the
    state out."""
    lag = 2
    cfg, plan, server = _server(decouple_bwd=True, apply_lag=lag)
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server))
    try:
        tr = obs.enable()
        try:
            for i in range(5):
                client.train_step(*_batch(i), i)
                dec = server.health()["decoupled_bwd"]
                assert dec["deferred_apply_depth"] == min(i + 1, lag)
                assert (dec["deferred_enqueued"]
                        - dec["deferred_applied"]) <= lag
        finally:
            obs.disable()
        # traced runs feed the reply/apply histograms (the
        # zero-overhead-off contract keeps them empty untraced):
        # reply_grad saw every step, deferred_apply only the drained ones
        snap = server.metrics()
        assert snap["histograms"]["reply_grad"]["count"] == 5
        assert snap["histograms"]["deferred_apply"]["count"] == 3
        names = [s["name"] for s in tr.spans()]
        assert names.count("reply_grad") == 5
        assert names.count("deferred_apply") == 3
        state = server.export_state()
        dec = server.health()["decoupled_bwd"]
        assert dec["deferred_apply_depth"] == 0
        assert dec["deferred_applied"] == dec["deferred_enqueued"] == 5
        assert int(state.step) == 5  # every update landed in the state
        # predict is a flush barrier too: after more traffic it reads
        # caught-up params
        client.train_step(*_batch(5), 5)
        assert server.health()["decoupled_bwd"]["deferred_apply_depth"] == 1
        import jax.numpy as jnp
        acts = np.asarray(plan.stages[0].apply(
            client.state.params, jnp.asarray(_batch(0)[0])))
        server.predict(acts)
        assert server.health()["decoupled_bwd"]["deferred_apply_depth"] == 0
    finally:
        server.close()


def test_close_drains_rather_than_drops():
    cfg, plan, server = _server(decouple_bwd=True, apply_lag=3)
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server))
    for i in range(2):
        client.train_step(*_batch(i), i)
    assert server.health()["decoupled_bwd"]["deferred_apply_depth"] == 2
    server.close()
    dec = server.health()["decoupled_bwd"]
    assert dec["deferred_apply_depth"] == 0
    assert dec["deferred_applied"] == 2  # applied, not discarded


def test_sync_bottoms_flushes_the_server_half():
    """MultiClientSplitRunner.sync_bottoms is a fleet consistency
    barrier: it must drain the shared server's queue before FedAvg'ing
    the bottoms (the satellite fix)."""
    cfg, plan, server = _server(decouple_bwd=True, apply_lag=3)
    runner = MultiClientSplitRunner(
        plan, cfg, jax.random.PRNGKey(1),
        lambda i: LocalTransport(server), num_clients=2)
    try:
        runner.train_round([_batch(0), _batch(1)])
        assert server.health()["decoupled_bwd"]["deferred_apply_depth"] == 2
        runner.sync_bottoms()
        assert server.health()["decoupled_bwd"]["deferred_apply_depth"] == 0
    finally:
        runner.close()
        server.close()


# ---------------------------------------------------------------------- #
# replay: a served duplicate never re-enqueues an apply
# ---------------------------------------------------------------------- #

def test_replay_duplicate_does_not_double_apply():
    cfg, plan, server = _server(decouple_bwd=True, apply_lag=2)
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server))
    try:
        x, y = _batch(0)
        loss0 = client.train_step(x, y, 0)
        dec = server.health()["decoupled_bwd"]
        assert dec["deferred_enqueued"] == 1
        # the retransmit: same (client, op, step) straight at the
        # server. The replay claim is taken before the payload is even
        # looked at, so the duplicate is served the cached reply — the
        # payload here is deliberately garbage to prove it
        _g_dup, loss_dup = server.split_step(
            np.zeros((1, 1), np.float32), y, 0, 0)
        assert loss_dup == loss0  # served the original reply
        dec = server.health()["decoupled_bwd"]
        assert dec["deferred_enqueued"] == 1  # no second enqueue
        assert server.replay.hits >= 1
    finally:
        server.close()


# ---------------------------------------------------------------------- #
# coalesced groups: replies land before the queued group apply
# ---------------------------------------------------------------------- #

def test_group_reply_before_apply():
    cfg, plan, server = _server(decouple_bwd=True, apply_lag=1,
                                coalesce_max=2)
    runner = MultiClientSplitRunner(
        plan, cfg, jax.random.PRNGKey(1),
        lambda i: LocalTransport(server),
        num_clients=2, concurrent=True)
    try:
        losses = runner.train_round([_batch(0), _batch(1)])
        # both replies are back (finite losses) while the round's group
        # update(s) are still queued: depth == 1 whether the round
        # coalesced into one group or dispatched two (push -> drain
        # keeps exactly lag entries pending)
        assert all(np.isfinite(l) for l in losses)
        dec = server.health()["decoupled_bwd"]
        assert dec["deferred_apply_depth"] == 1
        assert dec["deferred_enqueued"] - dec["deferred_applied"] == 1
        applied = server.flush_deferred()
        assert applied == 1
        assert server.health()["decoupled_bwd"]["deferred_apply_depth"] == 0
        # a second round still trains: the deferred group program is
        # compiled and the state advances
        losses2 = runner.train_round([_batch(2), _batch(3)])
        assert all(np.isfinite(l) for l in losses2)
    finally:
        runner.close()
        server.close()


# ---------------------------------------------------------------------- #
# checkpoint: mid-lag export round-trips
# ---------------------------------------------------------------------- #

def test_checkpoint_mid_lag_round_trips():
    """A checkpoint taken while updates are queued (export_state
    flushes first) must resume to the exact trajectory the original,
    flushed run continues on."""
    def run_a():
        cfg, plan, server = _server(decouple_bwd=True, apply_lag=2)
        client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                    LocalTransport(server))
        try:
            for i in range(3):
                client.train_step(*_batch(i), i)
            server.export_state()  # the mid-lag checkpoint flush
            return [client.train_step(*_batch(i), i) for i in range(3, 6)]
        finally:
            server.close()

    def run_b():
        cfg, plan, server = _server(decouple_bwd=True, apply_lag=2)
        client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                    LocalTransport(server))
        for i in range(3):
            client.train_step(*_batch(i), i)
        tree = server.export_state()
        assert server.health()["decoupled_bwd"]["deferred_apply_depth"] == 0
        server.close()
        # restart: a fresh server adopts the checkpoint; the client's
        # transport is repointed (its own bottom state carries over,
        # exactly the single-party-restart topology of test_checkpoint)
        cfg2, plan2, server2 = _server(decouple_bwd=True, apply_lag=2)
        client.transport.server = server2
        try:
            server2.resume_from(tree, 3)
            return [client.train_step(*_batch(i), i) for i in range(3, 6)]
        finally:
            server2.close()

    assert run_a() == run_b()


# ---------------------------------------------------------------------- #
# dispatch hygiene: both new programs are steady-state recompile free
# ---------------------------------------------------------------------- #

def test_decoupled_programs_steady_state_recompile_free():
    dd = dispatch_debug.tracker()
    g0 = dd.gauges()
    dispatch_debug.force(True)
    try:
        cfg, plan, server = _server(decouple_bwd=True, apply_lag=1)
        client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                    LocalTransport(server))
        try:
            for i in range(5):
                client.train_step(*_batch(i), i)
            server.flush_deferred()
        finally:
            server.close()
    finally:
        dispatch_debug.force(False)
    g1 = dd.gauges()
    assert (g1["steady_state_recompiles"]
            - g0["steady_state_recompiles"]) == 0
