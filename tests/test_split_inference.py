"""Split-party inference (runtime/evaluate.py evaluate_remote +
ServerRuntime.predict + the /predict route).

The reference's capability is training-only; serving is the natural
counterpart: the client holds only its own stages (and the labels), the
server answers forward-only /predict with ITS weights — no loss, no
optimizer step, no step handshake, so inference can interleave with
training without desyncing the handshake.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from split_learning_tpu.models import get_plan
from split_learning_tpu.runtime import ServerRuntime
from split_learning_tpu.runtime.evaluate import evaluate, evaluate_remote
from split_learning_tpu.transport import LocalTransport
from split_learning_tpu.utils import Config


def _setup(mode):
    plan = get_plan(mode=mode)
    rs = np.random.RandomState(0)
    x = rs.randn(48, 28, 28, 1).astype(np.float32)
    y = rs.randint(0, 10, (48,)).astype(np.int64)
    cfg = Config(mode=mode, batch_size=16)
    runtime = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x[:16])
    # same seed => the "client checkpoint" params equal the runtime's init
    all_params = plan.init(jax.random.PRNGKey(0), jnp.asarray(x[:16]))
    client_params = [all_params[i] for i in plan.stages_of("client")]
    from split_learning_tpu.data.datasets import Split
    return plan, runtime, all_params, client_params, Split(x=x, y=y)


@pytest.mark.parametrize("mode", ["split", "u_split"])
def test_remote_matches_full_composition(mode):
    """Client-side stages + /predict must reproduce evaluate() of the
    full composition (same params both sides by construction)."""
    plan, runtime, all_params, client_params, split = _setup(mode)
    transport = LocalTransport(runtime, through_codec=True)
    want = evaluate(plan, all_params, split, batch_size=16)
    got = evaluate_remote(plan, client_params, transport, split,
                          batch_size=16)
    assert got["examples"] == want["examples"] == 48
    np.testing.assert_allclose(got["loss"], want["loss"], rtol=1e-5)
    assert got["accuracy"] == want["accuracy"]


def test_predict_does_not_advance_the_handshake(mode="split"):
    """Inference between training steps must not move the step handshake
    or mutate server weights."""
    plan, runtime, all_params, client_params, split = _setup(mode)
    transport = LocalTransport(runtime)
    acts = transport.predict(np.asarray(
        plan.stages[0].apply(client_params[0], jnp.asarray(split.x[:4]))))
    assert acts.shape[0] == 4
    assert runtime.health()["step"] == -1  # untouched
    before = jax.tree_util.tree_leaves(runtime.state.params)[0]
    transport.predict(np.asarray(
        plan.stages[0].apply(client_params[0], jnp.asarray(split.x[:4]))))
    after = jax.tree_util.tree_leaves(runtime.state.params)[0]
    np.testing.assert_array_equal(np.asarray(before), np.asarray(after))


def test_predict_rejected_in_federated_mode():
    from split_learning_tpu.runtime.server import ProtocolError

    plan = get_plan(mode="federated")
    rs = np.random.RandomState(0)
    x = rs.randn(8, 28, 28, 1).astype(np.float32)
    cfg = Config(mode="federated", batch_size=8)
    runtime = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x)
    with pytest.raises(ProtocolError):
        runtime.predict(x)


def test_remote_over_http_wire():
    """The /predict route end to end: stdlib HTTP server, msgpack+CRC
    codec, metrics parity vs the composed plan."""
    from split_learning_tpu.transport.http import (HttpTransport,
                                                   SplitHTTPServer)

    plan, runtime, all_params, client_params, split = _setup("split")
    server = SplitHTTPServer(runtime).start()
    transport = HttpTransport(server.url)
    try:
        want = evaluate(plan, all_params, split, batch_size=24)
        got = evaluate_remote(plan, client_params, transport, split,
                              batch_size=24)
        np.testing.assert_allclose(got["loss"], want["loss"], rtol=1e-5)
        assert got["accuracy"] == want["accuracy"]
    finally:
        transport.close()
        server.stop()


@pytest.mark.slow
def test_remote_generation_matches_local_decode():
    """Split-party decode (one /predict round trip per token) is
    token-exact against the local composed-plan decode, greedy and
    sampled with filters, on both LM plan shapes."""
    from split_learning_tpu.models.transformer import transformer_plan
    from split_learning_tpu.runtime.generate import (generate_remote,
                                                     greedy_generate,
                                                     sample_generate)

    rs = np.random.RandomState(3)
    prompt = rs.randint(0, 64, (2, 6)).astype(np.int32)
    for mode in ("split", "u_split"):
        plan = transformer_plan(mode=mode, lm=True, vocab=64, d_model=16,
                                num_heads=1, max_len=64)
        params = plan.init(jax.random.PRNGKey(5), jnp.asarray(prompt))
        client_params = [params[i] for i in plan.stages_of("client")]
        cfg = Config(mode=mode, batch_size=2)
        runtime = ServerRuntime(plan, cfg, jax.random.PRNGKey(5),
                                np.asarray(prompt))
        transport = LocalTransport(runtime, through_codec=True)

        want = np.asarray(greedy_generate(plan, params, prompt, 5,
                                          kv_cache=False))
        got = generate_remote(plan, client_params, transport, prompt, 5)
        np.testing.assert_array_equal(got, want)

        rng = jax.random.PRNGKey(11)
        want_s = np.asarray(sample_generate(plan, params, prompt, 5, rng,
                                            0.8, top_k=5, kv_cache=False))
        got_s = generate_remote(plan, client_params, transport, prompt, 5,
                                rng=rng, temperature=0.8, top_k=5)
        np.testing.assert_array_equal(got_s, want_s)

        # sampling knobs without an rng are an error, never silent greedy
        with pytest.raises(ValueError, match="rng"):
            generate_remote(plan, client_params, transport, prompt, 5,
                            temperature=0.8)
