"""End-to-end smoke of the trace toolchain: a short traced CLI train
(`--trace PATH`) followed by scripts/trace_report.py over the artifact it
wrote. The unit pins in tests/test_obs.py freeze the span names and the
report's arithmetic; this test freezes the seam between them — the CLI
must keep writing a Chrome trace the report can summarize, and every
unconditional report section must actually render from a real run."""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_trace_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(REPO, "scripts", "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def traced_run(tmp_path_factory):
    """One 3-step traced local split train, shared by the cases below."""
    from split_learning_tpu.launch import run as launch_run
    trace = tmp_path_factory.mktemp("trace") / "train.trace.json"
    rc = launch_run.main([
        "train", "--mode", "split", "--transport", "local",
        "--dataset", "synthetic", "--steps", "3", "--batch-size", "4",
        "--trace", str(trace)])
    assert rc == 0
    assert trace.exists()
    return trace


def test_cli_trace_is_chrome_loadable(traced_run):
    with open(traced_run) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    assert any(e.get("ph") == "X" for e in events)


def test_trace_report_renders_every_section(traced_run, capsys):
    tr = _load_trace_report()
    assert tr.main([str(traced_run)]) == 0
    out = capsys.readouterr().out
    # the unconditional sections, in render() order
    assert "phase" in out and "count" in out          # per-phase table
    assert "client phase mix" in out
    assert "-> transport fraction:" in out
    assert "transport decomposition (total seconds):" in out
    assert "accounting: client spans sum to" in out
    # a real local run must have stepped through the client phases
    for phase in ("client_fwd", "transport", "step_total"):
        assert phase in out, f"phase {phase!r} missing from\n{out}"


def test_trace_report_json_schema(traced_run, capsys):
    tr = _load_trace_report()
    assert tr.main([str(traced_run), "--json", "--tenants", "2"]) == 0
    rep = json.loads(capsys.readouterr().out)
    for key in ("events", "spans", "phases", "client_phase_mix",
                "transport_fraction", "transport_decomposition_s",
                "compile", "decoupled_bwd", "mesh",
                "span_sum_over_wall_clock", "tenant_queue_wait"):
        assert key in rep, key
    assert rep["spans"] > 0
    assert 0.0 < rep["transport_fraction"] < 1.0
    # accounting gate from the report's own epilogue: the client spans
    # must cover step_total wall clock (within the documented 10%)
    assert rep["span_sum_over_wall_clock"] == pytest.approx(1.0, abs=0.1)
    # coupled local run: the conditional sections stay conditional
    assert rep["decoupled_bwd"] is None
