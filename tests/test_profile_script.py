"""scripts/profile_fused_tpu.py — the trace-summary machinery, driven
against a real (CPU) jax.profiler capture. The on-chip run happens via
the window runner; what must not rot silently is the Perfetto parsing
that turns a trace into the committed op-table artifact."""

import importlib.util
import os
import sys

import jax
import jax.numpy as jnp
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def mod():
    path = os.path.join(REPO, "scripts", "profile_fused_tpu.py")
    spec = importlib.util.spec_from_file_location("pft", path)
    m = importlib.util.module_from_spec(spec)
    sys.path.insert(0, REPO)
    spec.loader.exec_module(m)
    return m


@pytest.fixture(scope="module")
def trace_dir(tmp_path_factory):
    from split_learning_tpu.utils.profiling import device_trace

    d = str(tmp_path_factory.mktemp("trace"))

    @jax.jit
    def f(x):
        return (x @ x).sum()

    x = jnp.ones((256, 256))
    f(x).block_until_ready()
    with device_trace(d):
        for _ in range(3):
            f(x).block_until_ready()
    return d


def test_newest_trace_finds_the_capture(mod, trace_dir):
    path = mod.newest_trace(trace_dir)
    assert path is not None and path.endswith(".trace.json.gz")
    assert mod.newest_trace(trace_dir + "/nonexistent") is None


def test_summarize_trace_groups_by_process(mod, trace_dir):
    summary = mod.summarize_trace(mod.newest_trace(trace_dir), top_n=5)
    assert summary, "no processes parsed from the trace"
    for proc, ops in summary.items():
        assert 0 < len(ops) <= 5
        # sorted by total time, every record well-formed
        totals = [o["total_us"] for o in ops]
        assert totals == sorted(totals, reverse=True)
        for o in ops:
            assert o["count"] >= 1 and o["mean_us"] > 0


def test_profile_batch_env_unification(mod, monkeypatch, capsys):
    """SLT_PROFILE_BATCH is the knob's pre-unification name: honored
    alone (with a deprecation warning), refused when it disagrees with
    SLT_BENCH_BATCH — silently profiling a different shape than the
    bench leg it claims to corroborate is the failure mode."""
    monkeypatch.delenv("SLT_BENCH_BATCH", raising=False)
    monkeypatch.delenv("SLT_PROFILE_BATCH", raising=False)
    assert mod.profile_batch() == 64  # the bench legs' shared default

    monkeypatch.setenv("SLT_BENCH_BATCH", "32")
    assert mod.profile_batch() == 32

    monkeypatch.delenv("SLT_BENCH_BATCH")
    monkeypatch.setenv("SLT_PROFILE_BATCH", "16")
    assert mod.profile_batch() == 16
    assert "deprecated" in capsys.readouterr().err

    # agreement is tolerated (a transition-period invocation exporting
    # both identically keeps working)
    monkeypatch.setenv("SLT_BENCH_BATCH", "16")
    assert mod.profile_batch() == 16

    monkeypatch.setenv("SLT_BENCH_BATCH", "32")
    with pytest.raises(SystemExit, match="conflicts"):
        mod.profile_batch()
