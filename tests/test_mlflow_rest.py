"""MLflow round trip over the REST wire protocol (tracking/mlflow_rest.py).

The reference logs into a live MLflow server every step
(``src/server_part.py:19-23,55``); the mlflow *package* is absent in this
image, so the round trip is proven against a hermetic stub tracking
server that implements the same REST endpoints the real server exposes
(experiments/get-by-name, experiments/create, runs/create,
runs/log-metric, runs/log-batch, runs/update). The assertion is that
records actually LAND in the backend — experiment name, metric key/step
series, run lifecycle — not merely that requests were attempted.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from split_learning_tpu.tracking.logger import make_logger
from split_learning_tpu.tracking.mlflow_rest import MlflowRestLogger
from split_learning_tpu.utils import Config


class _StubMlflow(BaseHTTPRequestHandler):
    """Minimal MLflow tracking backend: an in-memory store behind the
    REST API 2.0 surface MlflowRestLogger uses."""

    store = None  # set per server instance

    def log_message(self, *a):  # quiet
        pass

    def do_POST(self):
        body = json.loads(self.rfile.read(
            int(self.headers.get("Content-Length", 0)) or 0) or b"{}")
        path = self.path.split("/api/2.0/mlflow/", 1)[-1]
        st = self.store
        if path == "experiments/get-by-name":
            name = body["experiment_name"]
            if name not in st["experiments"]:
                return self._reply(404, {"error_code":
                                         "RESOURCE_DOES_NOT_EXIST"})
            return self._reply(200, {"experiment": {
                "experiment_id": st["experiments"][name], "name": name}})
        if path == "experiments/create":
            eid = str(len(st["experiments"]) + 1)
            st["experiments"][body["name"]] = eid
            return self._reply(200, {"experiment_id": eid})
        if path == "runs/create":
            rid = f"run{len(st['runs']) + 1}"
            st["runs"][rid] = {"experiment_id": body["experiment_id"],
                               "run_name": body.get("run_name"),
                               "status": "RUNNING", "metrics": [],
                               "params": {}}
            return self._reply(200, {"run": {"info": {"run_id": rid}}})
        if path == "runs/log-metric":
            st["runs"][body["run_id"]]["metrics"].append(
                (body["key"], body["value"], body["step"]))
            return self._reply(200, {})
        if path == "runs/log-batch":
            run = st["runs"][body["run_id"]]
            for p in body.get("params", []):
                run["params"][p["key"]] = p["value"]
            return self._reply(200, {})
        if path == "runs/update":
            st["runs"][body["run_id"]]["status"] = body["status"]
            return self._reply(200, {})
        return self._reply(404, {"error_code": "ENDPOINT_NOT_FOUND"})

    def _reply(self, code: int, payload: dict):
        data = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


@pytest.fixture()
def mlflow_server():
    handler = type("H", (_StubMlflow,), {"store": {
        "experiments": {}, "runs": {}}})
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_port}", handler.store
    finally:
        srv.shutdown()
        srv.server_close()


def test_metrics_land_in_the_backend(mlflow_server):
    uri, store = mlflow_server
    with MlflowRestLogger("split", tracking_uri=uri) as lg:
        lg.log_params({"lr": 0.01, "batch_size": 64})
        for step, loss in enumerate([2.3, 1.9, 1.4]):
            lg.log_metric("loss", loss, step=step)

    # experiment + run naming parity with the reference server
    # (src/server_part.py:20-23)
    assert "Split_Learning_Sim" in store["experiments"]
    (rid, run), = store["runs"].items()
    assert run["run_name"] == "Split_Training"
    assert run["experiment_id"] == store["experiments"]["Split_Learning_Sim"]
    # the loss@step series actually landed, in order
    assert run["metrics"] == [("loss", 2.3, 0), ("loss", 1.9, 1),
                              ("loss", 1.4, 2)]
    assert run["params"] == {"lr": "0.01", "batch_size": "64"}
    assert run["status"] == "FINISHED"


def test_experiment_reused_across_runs(mlflow_server):
    uri, store = mlflow_server
    MlflowRestLogger("federated", tracking_uri=uri).close()
    MlflowRestLogger("federated", tracking_uri=uri).close()
    assert list(store["experiments"]) == ["Federated_Learning_Sim"]
    assert len(store["runs"]) == 2
    assert all(r["status"] == "FINISHED" for r in store["runs"].values())


def test_make_logger_falls_back_to_rest(mlflow_server, monkeypatch, capsys):
    """tracking='mlflow' with no mlflow package but a configured server
    URI must take the REST path (the round trip the reference topology
    exercises), not degrade to stdout."""
    uri, store = mlflow_server
    cfg = Config(tracking="mlflow", tracking_uri=uri)
    lg = make_logger(cfg)
    try:
        import mlflow  # noqa: F401
        pytest.skip("mlflow package present: the package path is used")
    except ImportError:
        pass
    assert isinstance(lg, MlflowRestLogger)
    lg.log_metric("loss", 0.5, step=7)
    lg.close()
    (rid, run), = store["runs"].items()
    assert run["metrics"] == [("loss", 0.5, 7)]


def test_unreachable_server_degrades_to_stdout(capsys):
    """A configured-but-dead MLflow URI must not abort training: the
    logger factory degrades to stdout with a warning (the same behavior
    the package path always had)."""
    from split_learning_tpu.tracking.logger import StdoutLogger
    try:
        import mlflow  # noqa: F401
        pytest.skip("mlflow package present: the package path is used")
    except ImportError:
        pass
    cfg = Config(tracking="mlflow",
                 tracking_uri="http://127.0.0.1:9")  # discard port: refused
    lg = make_logger(cfg)
    assert isinstance(lg, StdoutLogger)
    assert "unusable" in capsys.readouterr().err
