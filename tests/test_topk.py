"""Device-side topk8 kernels (ops/topk.py) vs the host wire reference.

Three implementations of the same selection rule must agree: the Pallas/
lax.top_k path here, the NumPy reference in transport/codec.py, and the
C++ kernel in native/slt_codec.cc (the latter two are parity-tested in
test_native.py). Kernels run in Mosaic interpreter mode on the CPU test
mesh; the same code compiles on real TPU.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from split_learning_tpu.ops.topk import (
    magnitudes,
    topk8_decode,
    topk8_encode,
    topk8_residual,
    topk8_roundtrip,
)
from split_learning_tpu.transport import codec


CUT_SHAPE = (64, 26, 26, 32)  # the real cut-layer activation (5.28 MiB)


def _host_encode(x: np.ndarray, k: int):
    """The wire-side reference: codec's selection + q8 scale math."""
    flat = np.ascontiguousarray(x, np.float32).reshape(-1)
    idx, vals = codec._topk8_select_numpy(flat, k)
    scale = max(float(np.max(np.abs(vals))) / 127.0, 1e-12)
    q = np.clip(np.round(vals / scale), -127, 127).astype(np.int8)
    return idx, q, scale


@pytest.mark.parametrize("shape", [(8, 26, 26, 32), CUT_SHAPE])
def test_encode_matches_host_reference(rng, shape):
    """Single-block and gridded (the full cut tensor spans many row
    blocks): same index set, same scale, survivors within 1 LSB."""
    x = jax.random.normal(rng, shape, jnp.float32) * 3.0
    n = int(np.prod(shape))
    k = max(1, int(math.ceil(0.1 * n)))
    idx_d, q_d, s_d = topk8_encode(x, k)
    idx_h, q_h, s_h = _host_encode(np.asarray(x), k)
    np.testing.assert_array_equal(np.sort(np.asarray(idx_d)), idx_h)
    assert float(s_d) == pytest.approx(s_h, rel=1e-6)
    # same positions, so compare values position-by-position
    order = np.argsort(np.asarray(idx_d))
    assert int(np.max(np.abs(
        np.asarray(q_d)[order].astype(np.int32) - q_h.astype(np.int32)))) <= 1


def test_magnitudes_is_abs(rng):
    x = jax.random.normal(rng, CUT_SHAPE, jnp.float32)
    np.testing.assert_allclose(np.asarray(magnitudes(x)),
                               np.abs(np.asarray(x)).reshape(-1),
                               rtol=0, atol=0)


def test_tie_break_toward_lower_indices():
    """lax.top_k's stable tie-breaking matches the host rule: on an
    all-equal tensor, the first k indices win."""
    x = jnp.ones((4, 64), jnp.float32)
    idx, q, scale = topk8_encode(x, 10)
    np.testing.assert_array_equal(np.sort(np.asarray(idx)),
                                  np.arange(10, dtype=np.int32))
    idx_h, _, _ = _host_encode(np.ones((4, 64), np.float32), 10)
    np.testing.assert_array_equal(np.sort(np.asarray(idx)), idx_h)


def test_roundtrip_error_bound(rng):
    """Survivors reconstruct within half a quantization step; dropped
    elements decode as exactly zero."""
    x = jax.random.normal(rng, (16, 26, 26, 32), jnp.float32) * 2.0
    n = x.size
    k = int(math.ceil(0.1 * n))
    out = np.asarray(topk8_roundtrip(x, k))
    xn = np.asarray(x)
    idx, _, scale = topk8_encode(x, k)
    mask = np.zeros(n, bool)
    mask[np.asarray(idx)] = True
    flat_x, flat_o = xn.reshape(-1), out.reshape(-1)
    assert np.all(flat_o[~mask] == 0.0)
    assert float(np.max(np.abs(flat_o[mask] - flat_x[mask]))) <= (
        float(scale) * 0.5 + 1e-6)


def test_residual_is_exact_complement(rng):
    """residual + decode == x exactly at survivors (same subtraction),
    and the residual equals x at dropped positions — nothing is lost."""
    x = jax.random.normal(rng, (8, 26, 26, 32), jnp.float32)
    idx, q, scale = topk8_encode(x, 2000)
    dec = topk8_decode(idx, q, scale, x.shape, x.dtype)
    res = topk8_residual(x, idx, q, scale)
    np.testing.assert_allclose(np.asarray(res) + np.asarray(dec),
                               np.asarray(x), rtol=0, atol=1e-6)


def test_encode_under_jit(rng):
    """Static k keeps shapes jit-stable (density is a config knob)."""
    x = jax.random.normal(rng, (8, 26, 26, 32), jnp.float32)

    @jax.jit
    def f(t):
        return topk8_encode(t, 512)

    idx, q, scale = f(x)
    assert idx.shape == (512,) and q.shape == (512,)
    assert q.dtype == jnp.int8 and idx.dtype == jnp.int32


def test_encode_rejects_bad_k(rng):
    x = jax.random.normal(rng, (4, 4), jnp.float32)
    with pytest.raises(ValueError):
        topk8_encode(x, 0)
    with pytest.raises(ValueError):
        topk8_encode(x, 17)
