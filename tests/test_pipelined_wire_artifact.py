"""The committed real-wire pipelined-overlap artifact
(``artifacts/pipelined_wire.json``, written by
``scripts/measure_pipelined_wire.py``) — VERDICT r4 weak #5 closure.

Round 4's >1x overlap claim rested on ``time.sleep`` inside one
process; the artifact these tests pin measures the depth-W window
against a lock-step client across THREE OS processes with the latency
injected at the socket layer (a propagation-delay proxy), at more than
one wire latency. The tests assert the artifact's provenance says so,
that the delivered latency was actually measured (not assumed), and
that the claim itself — overlap hides the wire, in proportion to the
wire's share of the step — holds in the recorded numbers.
"""

import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "artifacts", "pipelined_wire.json")


@pytest.fixture(scope="module")
def art():
    if not os.path.exists(ARTIFACT):
        pytest.skip(f"missing {ARTIFACT}; run "
                    "scripts/measure_pipelined_wire.py")
    with open(ARTIFACT) as f:
        return json.load(f)


def test_real_concurrency_provenance(art):
    """The claim must rest on separate OS processes and socket-layer
    delay — never an in-process sleep."""
    topo = art["provenance"]["topology"]
    assert "OS processes" in topo
    assert "no in-process sleeps" in topo
    assert len(art["points"]) >= 2, (
        "a single latency point cannot show the overlap win scaling "
        "with the wire's share of the step")
    for p in art["points"]:
        # the configured delay was verified on the wire, not assumed:
        # the delivered figure includes HTTP/TCP overhead so it must
        # be at least the configured propagation delay
        assert p["one_way_delay_measured_ms"] >= \
            p["one_way_delay_configured_ms"]


def test_overlap_beats_lock_step_where_wire_matters(art):
    depth = art["depth"]
    assert depth >= 2
    for p in art["points"]:
        sync = p["steps_per_sec_sync"]
        piped = p[f"steps_per_sec_depth{depth}"]
        assert p["pipelining_speedup"] == pytest.approx(piped / sync,
                                                        rel=1e-3)
    # at the highest-latency point the wire is a large share of the
    # step: the in-flight window must actually win there
    top = max(art["points"],
              key=lambda p: p["one_way_delay_measured_ms"])
    assert top["pipelining_speedup"] > 1.1, (
        "depth-W window no faster than lock-step on a real wire — "
        "the overlap machinery is not overlapping")
    # and the win must grow with the wire's share (allowing noise at
    # the low end, where there is ~nothing to hide)
    by_delay = sorted(art["points"],
                      key=lambda p: p["one_way_delay_measured_ms"])
    assert by_delay[-1]["pipelining_speedup"] >= \
        by_delay[0]["pipelining_speedup"] - 0.05


def test_speedup_physically_plausible(art):
    """W in-flight lanes can overlap at most W steps' worth of
    hideable time (wire + serialization + scheduling dead time), so
    speedup is hard-capped by the window depth regardless of where the
    hidden time comes from. A number past it means the measurement
    timed dispatch, not execution (the round-1/2 failure mode this
    repo's gates exist for). A tighter wire-only cap is NOT asserted:
    on this one-core host the sync baseline's compute share moves
    ±40% with probe-subprocess contention (observed 2026-08-01), so a
    per-point compute/wire decomposition would gate on noise — the
    artifact's note records that the overlap hides per-request
    overheads alongside the injected wire."""
    depth = art["depth"]
    for p in art["points"]:
        assert p["pipelining_speedup"] <= depth, (
            f"speedup {p['pipelining_speedup']} at "
            f"{p['one_way_delay_measured_ms']}ms exceeds the "
            f"depth-{depth} window's hard cap")
        # both runs must be real execution at sane absolute rates —
        # noise-immune wire floors: lock-step pays the full measured
        # RTT per step, and even W perfectly overlapped lanes each
        # still pay it (so the windowed rate floors at RTT/W per step)
        rtt_s = 2 * p["one_way_delay_measured_ms"] / 1e3
        assert 1.0 / p["steps_per_sec_sync"] >= rtt_s * 0.9
        assert 1.0 / p[f"steps_per_sec_depth{depth}"] >= \
            (rtt_s / depth) * 0.9
