"""The committed real-wire pipelined-overlap artifact
(``artifacts/pipelined_wire.json``, written by
``scripts/measure_pipelined_wire.py``) — VERDICT r4 weak #5 closure.

Round 4's >1x overlap claim rested on ``time.sleep`` inside one
process; the artifact these tests pin measures the depth-W window
against a lock-step client across THREE OS processes with the latency
injected at the socket layer (a propagation-delay proxy). The tests
assert the artifact's provenance says so, that the delivered latency
was actually measured (not assumed), and that the claim itself —
overlap hides the wire — holds in the recorded numbers.
"""

import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "artifacts", "pipelined_wire.json")


@pytest.fixture(scope="module")
def art():
    if not os.path.exists(ARTIFACT):
        pytest.skip(f"missing {ARTIFACT}; run "
                    "scripts/measure_pipelined_wire.py")
    with open(ARTIFACT) as f:
        return json.load(f)


def test_real_concurrency_provenance(art):
    """The claim must rest on separate OS processes and socket-layer
    delay — never an in-process sleep."""
    topo = art["provenance"]["topology"]
    assert "OS processes" in topo
    assert "no in-process sleeps" in topo
    # the configured delay was verified on the wire, not assumed: the
    # delivered figure includes HTTP/TCP overhead so it must be at
    # least the configured propagation delay
    assert art["one_way_delay_measured_ms"] >= \
        art["one_way_delay_configured_ms"]


def test_overlap_beats_lock_step(art):
    depth = art["depth"]
    sync = art["steps_per_sec_sync"]
    piped = art[f"steps_per_sec_depth{depth}"]
    assert depth >= 2
    assert art["pipelining_speedup"] == pytest.approx(piped / sync,
                                                      rel=1e-3)
    # the in-flight window exists to hide the wire: at a wire delay
    # comparable to compute it must actually win
    assert art["pipelining_speedup"] > 1.1, (
        "depth-W window no faster than lock-step on a real wire — "
        "the overlap machinery is not overlapping")


def test_speedup_physically_plausible(art):
    """Overlap can at most hide the full round trip: speedup is capped
    by (compute + RTT) / compute — and never exceeds the window depth
    itself (W lanes can hide at most W steps of wire, which binds
    exactly when the wire dominates and the compute-based cap blows
    up). A number past either cap means the measurement timed
    dispatch, not execution (the round-1/2 failure mode this repo's
    gates exist for)."""
    sync = art["steps_per_sec_sync"]
    rtt_s = 2 * art["one_way_delay_measured_ms"] / 1e3
    step_s = 1.0 / sync                      # compute + RTT per step
    compute_s = step_s - rtt_s
    cap = step_s / compute_s if compute_s > 0 else float("inf")
    cap = min(cap, art["depth"])
    assert art["pipelining_speedup"] <= cap * 1.1, (
        f"speedup {art['pipelining_speedup']} exceeds the physical cap "
        f"{cap:.2f} implied by the measured wire and window depth")
