"""Sharded server runtime (PR 11): the server half pjit-compiled over a
named mesh, with mesh-aware coalesced dispatch.

Pins, in order: a mesh of size 1 normalizes to the legacy single-device
runtime and every path (fused serialized, coalesced groups-of-one, 2BP
lag-0/lag-2) is BIT-identical to ``mesh=None``; ``data=2`` reproduces
the same trajectories to float tolerance (different reduction shapes,
same math); the tensor-parallel layout shards the heavy leaves along
``model`` and still trains; coalesced groups pad to a multiple of the
``data`` axis with zero-weight rows that leave the objective untouched;
``predict`` pads/trims transparently while serialized training rejects
non-divisible batches with a protocol 400; the sanctioned per-shard
gather (slt-lint SLT013) trims to the requested rows and dedups
replicated shards; and the mesh shape + MFU accounting surfaces through
health()/metrics()/trace_metadata(). The suite runs on the forced
8-device CPU host topology from conftest.py, under both the lock and
dispatch watchdog teardown gates."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from split_learning_tpu import obs
from split_learning_tpu.models import get_plan
from split_learning_tpu.parallel.distributed import (SpecLayout,
                                                     server_state_layout)
from split_learning_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS,
                                              batch_sharding, host_gather,
                                              make_host_mesh, replicated)
from split_learning_tpu.runtime import ServerRuntime, SplitClientTrainer
from split_learning_tpu.runtime.server import ProtocolError
from split_learning_tpu.transport.local import LocalTransport
from split_learning_tpu.utils import Config

BATCH = 4


def _server(batch=BATCH, **kw):
    cfg = Config(mode="split", batch_size=batch, num_clients=2)
    plan = get_plan(mode="split")
    sample = np.zeros((batch, 28, 28, 1), np.float32)
    return cfg, plan, ServerRuntime(plan, cfg, jax.random.PRNGKey(2),
                                    sample, **kw)


def _batch(seed=0, batch=BATCH):
    rs = np.random.RandomState(seed)
    return (rs.randn(batch, 28, 28, 1).astype(np.float32),
            rs.randint(0, 10, batch).astype(np.int64))


def _series(steps=4, batch=BATCH, **kw):
    cfg, plan, server = _server(batch=batch, **kw)
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server))
    try:
        return [client.train_step(*_batch(i, batch), i)
                for i in range(steps)], server
    finally:
        server.close()


# ---------------------------------------------------------------------- #
# mesh=1 bit-identity: the degenerate mesh IS the legacy runtime
# ---------------------------------------------------------------------- #

def test_mesh1_is_normalized_and_bit_identical_fused():
    """A size-1 mesh compiles the very same legacy ``jax.jit`` programs
    (the ctor normalizes it to ``mesh=None``), so the loss series is
    IDENTICAL — not merely close."""
    legacy, _ = _series()
    m1, srv = _series(mesh=make_host_mesh(data=1))
    assert srv._mesh is None          # normalized, not special-cased
    assert legacy == m1


def test_mesh1_bit_identical_coalesced_groups_of_one():
    """Window flushes of one route through the mesh-aware group dispatch
    (padding, zero weights, rows-bounded gather); on a size-1 mesh that
    path must still be bit-for-bit the legacy coalesced path."""
    legacy, _ = _series(coalesce_max=4, coalesce_window_ms=5.0)
    m1, _ = _series(coalesce_max=4, coalesce_window_ms=5.0,
                    mesh=make_host_mesh(data=1))
    assert legacy == m1


@pytest.mark.parametrize("lag", [0, 2])
def test_mesh1_bit_identical_decoupled_bwd(lag):
    legacy, _ = _series(decouple_bwd=True, apply_lag=lag)
    m1, _ = _series(decouple_bwd=True, apply_lag=lag,
                    mesh=make_host_mesh(data=1))
    assert legacy == m1


# ---------------------------------------------------------------------- #
# data=2: same math, different reduction shapes -> float tolerance
# ---------------------------------------------------------------------- #

def test_data2_fused_matches_to_float_tolerance():
    legacy, _ = _series()
    d2, srv = _series(mesh=make_host_mesh(data=2))
    assert srv is not None
    np.testing.assert_allclose(d2, legacy, rtol=1e-4, atol=5e-4)


def test_data2_coalesced_and_decoupled_match():
    legacy_c, _ = _series(coalesce_max=4, coalesce_window_ms=5.0)
    d2_c, _ = _series(coalesce_max=4, coalesce_window_ms=5.0,
                      mesh=make_host_mesh(data=2))
    np.testing.assert_allclose(d2_c, legacy_c, rtol=1e-4, atol=5e-4)
    legacy_b, _ = _series(decouple_bwd=True, apply_lag=2)
    d2_b, _ = _series(decouple_bwd=True, apply_lag=2,
                      mesh=make_host_mesh(data=2))
    np.testing.assert_allclose(d2_b, legacy_b, rtol=1e-4, atol=5e-4)


def test_tensor_parallel_mesh_shards_heavy_leaves_and_trains():
    legacy, _ = _series()
    cfg, plan, server = _server(mesh=make_host_mesh(data=2, model=2))
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server))
    try:
        # divisible weight leaves actually land on the model axis
        specs = [tuple(leaf.sharding.spec)
                 for leaf in jax.tree_util.tree_leaves(server.state.params)]
        assert any(MODEL_AXIS in sp for sp in specs), specs
        tp = [client.train_step(*_batch(i), i) for i in range(4)]
        np.testing.assert_allclose(tp, legacy, rtol=1e-4, atol=5e-4)
    finally:
        server.close()


# ---------------------------------------------------------------------- #
# mesh-aware group sizing: pad to a multiple of the data axis
# ---------------------------------------------------------------------- #

def test_group_pads_to_data_axis_multiple_with_zero_weight_tail():
    """batch=2 on a data=4 mesh: the pow2 bucket (2) is SMALLER than the
    data axis, so the group must round up to 4 rows — and the two
    zero-weight padding rows must leave the loss series at the unsharded
    values (float tolerance)."""
    legacy, _ = _series(batch=2)
    padded, srv = _series(batch=2, coalesce_max=4, coalesce_window_ms=5.0,
                          mesh=make_host_mesh(data=4))
    np.testing.assert_allclose(padded, legacy, rtol=1e-4, atol=5e-4)
    sigs = list(srv._coalesce_shapes)
    assert sigs, "group dispatch never ran"
    for shape, _, _ in sigs:
        assert shape[0] % 4 == 0, sigs


# ---------------------------------------------------------------------- #
# serialized divisibility guard + predict pad/trim
# ---------------------------------------------------------------------- #

def test_serialized_nondivisible_batch_is_a_protocol_400():
    cfg, plan, server = _server(mesh=make_host_mesh(data=2))
    try:
        acts = np.zeros((3, 26, 26, 32), np.float32)  # cut-layer shape
        labels = np.zeros((3,), np.int64)
        with pytest.raises(ProtocolError, match="data") as exc:
            server.split_step(acts, labels, 0)
        assert exc.value.status == 400
    finally:
        server.close()


def test_predict_pads_and_trims_odd_batches():
    cfg, plan, server0 = _server()
    cfg2, plan2, server2 = _server(mesh=make_host_mesh(data=2))
    try:
        acts = np.random.RandomState(7).randn(3, 26, 26, 32).astype(
            np.float32)
        out0 = server0.predict(acts)
        out2 = server2.predict(acts)
        assert out2.shape == out0.shape == (3, 10)
        np.testing.assert_allclose(out2, out0, rtol=1e-5, atol=1e-5)
    finally:
        server0.close()
        server2.close()


def test_d2h_single_channel_serializes_concurrent_transfers():
    """With d2h_single_channel=True, N concurrent synthetic transfers
    reserve back-to-back windows on the one simulated DMA channel, so
    wall clock is bounded below by N*delay — the property that makes
    the sharded_server bench's dispatch-count amortization deterministic
    instead of a thread-phasing race. (Default False keeps the overlap
    benches' model: sleeps may overlap; no upper bound is asserted here
    because parallel-sleep timing is scheduler noise.)"""
    import threading
    import time as _time

    delay = 0.05
    _, _, server = _server(d2h_delay_s=delay, d2h_single_channel=True)
    try:
        threads = [threading.Thread(target=server._sleep_d2h)
                   for _ in range(3)]
        t0 = _time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert _time.monotonic() - t0 >= 3 * delay
    finally:
        server.close()


# ---------------------------------------------------------------------- #
# the sanctioned gather (SLT013) + mesh construction helpers
# ---------------------------------------------------------------------- #

def test_host_gather_trims_dedups_and_passes_through():
    mesh = make_host_mesh(data=2)
    x = jax.device_put(jnp.arange(12.0).reshape(6, 2),
                       batch_sharding(mesh))
    np.testing.assert_array_equal(host_gather(x),
                                  np.arange(12.0).reshape(6, 2))
    # rows bounds the transfer: only the first 3 rows come back
    np.testing.assert_array_equal(host_gather(x, rows=3),
                                  np.arange(6.0).reshape(3, 2))
    # replicated shards dedup — 2 device copies, one logical array
    r = jax.device_put(jnp.arange(4.0).reshape(2, 2), replicated(mesh))
    np.testing.assert_array_equal(host_gather(r),
                                  np.arange(4.0).reshape(2, 2))
    # host arrays pass through (with the same rows contract)
    h = np.arange(10.0).reshape(5, 2)
    np.testing.assert_array_equal(host_gather(h, rows=2), h[:2])
    # scalars fall back to plain materialization
    assert host_gather(jnp.float32(3.5)) == np.float32(3.5)


def test_make_host_mesh_reports_the_remedy_when_short_on_devices():
    with pytest.raises(RuntimeError, match="xla_force_host_platform"):
        make_host_mesh(data=64)


def test_spec_layout_rules():
    layout = server_state_layout(make_host_mesh(data=2, model=2))
    assert isinstance(layout, SpecLayout)
    assert (layout.data, layout.model) == (2, 2)
    # column-parallel: last dim divisible by the model axis
    col = layout.param(jnp.zeros((8, 64))).spec
    assert tuple(col) == (None, MODEL_AXIS)
    # row-parallel: only the second-to-last dim divides
    row = layout.param(jnp.zeros((64, 5))).spec
    assert tuple(row) == (MODEL_AXIS, None)
    # biases / scalars replicate
    assert tuple(layout.param(jnp.zeros((5,))).spec) == ()
    # batch layout shards dim 0 along data
    assert tuple(layout.batch().spec)[0] == DATA_AXIS


# ---------------------------------------------------------------------- #
# observability: health / metrics / trace metadata
# ---------------------------------------------------------------------- #

def test_mesh_surfaces_in_health_metrics_and_trace_metadata():
    cfg, plan, server = _server(mesh=make_host_mesh(data=2))
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server))
    try:
        client.train_step(*_batch(0), 0)
        mesh_h = server.health()["mesh"]
        assert mesh_h["devices"] == 2 and mesh_h["data"] == 2
        gauges = server.metrics()["gauges"]
        assert gauges["mesh_devices"] == 2.0
        assert gauges["mesh_data"] == 2.0
        # MFU accounting only runs while tracing (zero-overhead-off)
        meta0 = server.trace_metadata()
        assert meta0["programs"] == {}
        obs.enable()
        try:
            client.train_step(*_batch(1), 1)
        finally:
            obs.disable()
        meta = server.trace_metadata()
        assert meta["mesh"]["data"] == 2
        assert meta["gather_bytes"] > 0        # the sanctioned gather ran
        prog = meta["programs"]["split_step"]
        assert prog["calls"] >= 1
        assert prog["model_flops"] > 0
        # CPU backend: peak unknown -> MFU honestly None, never 0
        assert meta["peak_flops_per_device"] is None
        assert prog["mfu"] is None
    finally:
        server.close()


def test_unsharded_server_exports_no_mesh_or_gather_counters():
    cfg, plan, server = _server()
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server))
    try:
        client.train_step(*_batch(0), 0)
        assert "mesh" not in server.health()
        assert "gather_bytes" not in server.metrics()["counters"]
        meta = server.trace_metadata()
        assert meta["mesh"] == {"devices": 1, "data": 1}
        assert meta["gather_bytes"] == 0
    finally:
        server.close()


def test_federated_mesh_is_rejected():
    cfg = Config(mode="federated", batch_size=BATCH, num_clients=2)
    plan = get_plan(mode="federated")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    with pytest.raises(ValueError, match="federated"):
        ServerRuntime(plan, cfg, jax.random.PRNGKey(2), sample,
                      mesh=make_host_mesh(data=2))


# ---------------------------------------------------------------------- #
# checkpoint round-trip keeps the sharded layout
# ---------------------------------------------------------------------- #

def test_resume_from_reshards_and_continues():
    cfg, plan, server = _server(mesh=make_host_mesh(data=2))
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server))
    try:
        client.train_step(*_batch(0), 0)
        state = server.export_state()
        # round-trip through host-side state (the checkpoint shape)
        host_state = jax.tree_util.tree_map(np.asarray, state)
        server.resume_from(host_state, step=0)
        for leaf in jax.tree_util.tree_leaves(server.state.params):
            assert DATA_AXIS not in tuple(leaf.sharding.spec or ())
            assert leaf.sharding.mesh.size == 2
        loss = client.train_step(*_batch(1), 1)
        assert np.isfinite(loss)
    finally:
        server.close()
