"""Multi-client split learning (config 3): interleaved clients with
per-client handshakes against one shared server half."""

import jax
import numpy as np
import pytest

from split_learning_tpu.models import get_plan
from split_learning_tpu.runtime import ProtocolError, ServerRuntime
from split_learning_tpu.runtime.multi_client import MultiClientSplitRunner
from split_learning_tpu.transport import LocalTransport
from split_learning_tpu.utils import Config

BATCH = 8


def make(n_clients=2, **kw):
    cfg = Config(mode="split", batch_size=BATCH, num_clients=n_clients)
    plan = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), sample)
    runner = MultiClientSplitRunner(
        plan, cfg, jax.random.PRNGKey(0),
        transport_factory=lambda i: LocalTransport(server),
        num_clients=n_clients, **kw)
    return server, runner


def batches(n_clients, seed):
    rs = np.random.RandomState(seed)
    centers = rs.randn(10, 28 * 28).astype(np.float32)
    out = []
    for _ in range(n_clients):
        y = rs.randint(0, 10, (BATCH,))
        x = (centers[y] + 0.4 * rs.randn(BATCH, 28 * 28)).astype(np.float32)
        out.append((x.reshape(BATCH, 28, 28, 1), y.astype(np.int64)))
    return out


def test_interleaved_clients_with_per_client_handshake():
    server, runner = make(2)
    all_losses = []
    for r in range(12):
        losses = runner.train_round(batches(2, seed=r))
        all_losses.append(losses)
    # both clients' steps were accepted (per-client handshake tracking)
    assert server._last_step == {0: 11, 1: 11}
    # shared server half + per-client bottoms still learn
    assert np.mean(all_losses[-1]) < np.mean(all_losses[0]) * 0.7


def test_same_client_replay_served_from_cache_then_rejected():
    """Exactly-once within the replay window: a duplicate of an applied
    step is answered with the cached original (no re-apply, no 409);
    once evicted past the window, the strict-step 409 still holds."""
    server, runner = make(1)
    orig = runner.train_round(batches(1, seed=0))[0]
    client = runner.clients[0]
    x, y = batches(1, seed=1)[0]
    # duplicate of step 0: cached reply, server step unmoved
    assert client.train_step(x, y, step=0) == orig
    assert server.health()["step"] == 0
    assert server.replay.hits >= 1
    # evict step 0 out of the window, then the replay is a protocol error
    for r in range(1, server.replay.window + 2):
        runner.train_round(batches(1, seed=r))
    with pytest.raises(ProtocolError):
        client.train_step(x, y, step=0)


def test_bottom_sync_fedavg():
    server, runner = make(2)
    runner.sync_bottoms_every = 3
    for r in range(3):
        runner.train_round(batches(2, seed=r))
    a, b = (jax.tree_util.tree_leaves(c.state.params) for c in runner.clients)
    for la, lb in zip(a, b):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb))


def test_bottom_sync_compressed_delta_from_reference():
    """sync_compress='topk8' (PR 18): the FIRST sync has no agreed
    reference yet and goes dense — bit-identical to the legacy FedAvg
    mean — and every later sync ships topk8 deltas from the last mean
    (raw params are dense; inter-sync drift is sparse). Clients still
    agree exactly after every sync (one reconstructed mean is adopted
    by all) and the byte counters show real compression."""
    _, runner_c = make(2, sync_bottoms_every=3, sync_compress="topk8",
                       sync_density=0.1)
    _, runner_d = make(2, sync_bottoms_every=3)
    for r in range(3):
        runner_c.train_round(batches(2, seed=r))
        runner_d.train_round(batches(2, seed=r))
    # first sync fired at round 3 with no reference: dense, legacy-exact
    assert runner_c.sync_wire_bytes == 0
    for lc, ld in zip(
            jax.tree_util.tree_leaves(runner_c.clients[0].state.params),
            jax.tree_util.tree_leaves(runner_d.clients[0].state.params)):
        np.testing.assert_array_equal(np.asarray(lc), np.asarray(ld))
    for r in range(3, 6):
        runner_c.train_round(batches(2, seed=r))
    # second sync shipped sparse deltas...
    assert runner_c.sync_raw_bytes > runner_c.sync_wire_bytes > 0
    # ...and the cohort still agrees exactly
    a, b = (jax.tree_util.tree_leaves(c.state.params)
            for c in runner_c.clients)
    for la, lb in zip(a, b):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_concurrent_clients_are_race_free():
    """N clients stepping from threads against one shared server half:
    the runtime lock serializes state transitions (the reference's
    module-global-model version of this is a data race by construction,
    SURVEY.md §5 "Race detection"); per-client handshakes all advance."""
    import threading

    n_clients, n_steps = 4, 6
    cfg = Config(mode="split", batch_size=BATCH, num_clients=n_clients)
    plan = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), sample)

    from split_learning_tpu.runtime import SplitClientTrainer
    clients = [
        SplitClientTrainer(plan, cfg, jax.random.fold_in(
            jax.random.PRNGKey(0), i), LocalTransport(server), client_id=i)
        for i in range(n_clients)
    ]
    errors = []

    def run(i):
        try:
            data = batches(1, seed=100 + i)[0]
            for s in range(n_steps):
                loss = clients[i].train_step(*data, step=s)
                assert np.isfinite(loss)
        except Exception as exc:  # propagate to the main thread
            errors.append((i, exc))

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert server._last_step == {i: n_steps - 1 for i in range(n_clients)}


def test_round_survives_client_dropout():
    """A client whose wire dies mid-training (skip policy) must not take
    the round down with it: the other clients' steps land, the dropped
    client reports None, and when its wire comes back its handshake
    resumes — strict_steps accepts the gap (monotonic, not contiguous)."""
    from split_learning_tpu.transport.base import (
        FaultInjector, FaultyTransport)
    from split_learning_tpu.runtime.client import FailurePolicy

    n_clients = 2
    cfg = Config(mode="split", batch_size=BATCH, num_clients=n_clients)
    plan = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), sample)
    # client 1's wire fails on rounds 2-4; client 0's never does
    injector = FaultInjector(fail_steps={2, 3, 4})
    runner = MultiClientSplitRunner(
        plan, cfg, jax.random.PRNGKey(0),
        transport_factory=lambda i: (
            FaultyTransport(LocalTransport(server), injector) if i == 1
            else LocalTransport(server)),
        num_clients=n_clients)
    runner.clients[1].failure_policy = FailurePolicy.SKIP

    results = [runner.train_round(batches(n_clients, seed=r))
               for r in range(7)]
    for r, losses in enumerate(results):
        assert np.isfinite(losses[0])          # healthy client never blocked
        if r in (2, 3, 4):
            assert losses[1] is None           # dropped, not raised
        else:
            assert np.isfinite(losses[1])
    assert injector.injected == 3
    assert runner.clients[1].dropped_batches == 3
    # handshake resumed across the gap: both clients' last step accepted
    assert server._last_step == {0: 6, 1: 6}


def test_sync_bottoms_skips_uninitialized_clients():
    """FedAvg must average only clients that have trained: a client that
    dropped every step (state is None) contributes nothing and is left
    untouched — averaging in a zeros/None state would skew the fleet."""
    from split_learning_tpu.transport.base import (
        FaultInjector, FaultyTransport)
    from split_learning_tpu.runtime.client import FailurePolicy

    n_clients = 3
    cfg = Config(mode="split", batch_size=BATCH, num_clients=n_clients)
    plan = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), sample)
    # client 2 fails every step it ever attempts
    injector = FaultInjector(failure_rate=1.0)
    runner = MultiClientSplitRunner(
        plan, cfg, jax.random.PRNGKey(0),
        transport_factory=lambda i: (
            FaultyTransport(LocalTransport(server), injector) if i == 2
            else LocalTransport(server)),
        num_clients=n_clients, sync_bottoms_every=2)
    runner.clients[2].failure_policy = FailurePolicy.SKIP

    for r in range(4):
        losses = runner.train_round(batches(n_clients, seed=r))
        assert losses[2] is None
    # the two live clients were averaged together...
    a, b, c = (jax.tree_util.tree_leaves(runner.clients[i].state.params)
               for i in (0, 1, 2))
    for la, lb in zip(a, b):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb))
    # ...and the dead client (initialized but never stepped) was
    # excluded from the mean and left untouched
    assert int(runner.clients[2].state.step) == 0
    assert any(not np.array_equal(np.asarray(lc), np.asarray(la))
               for la, lc in zip(a, c))


def test_sync_bottoms_single_survivor_is_noop():
    """With one initialized client, FedAvg has nothing to average — the
    survivor's params must pass through bit-identical."""
    server, runner = make(2)
    runner.train_round(batches(2, seed=0))
    before = jax.tree_util.tree_leaves(runner.clients[0].state.params)
    runner.clients[1].state = None  # simulate a never-recovered dropout
    runner.sync_bottoms()
    after = jax.tree_util.tree_leaves(runner.clients[0].state.params)
    for la, lb in zip(before, after):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_dropout_with_topk8_wire_keeps_ef_consistent():
    """Dropout under the compressed wire: a skipped step must not corrupt
    the surviving clients' error-feedback state — per-(role, client) EF
    keys keep each client's residual independent, so client 0 converges
    while client 1 flaps."""
    from split_learning_tpu.transport.base import (
        FaultInjector, FaultyTransport)
    from split_learning_tpu.runtime.client import FailurePolicy

    n_clients = 2
    cfg = Config(mode="split", batch_size=BATCH, num_clients=n_clients)
    plan = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), sample)
    injector = FaultInjector(fail_steps={1, 3, 5})
    runner = MultiClientSplitRunner(
        plan, cfg, jax.random.PRNGKey(0),
        transport_factory=lambda i: (
            FaultyTransport(
                LocalTransport(server, compress="topk8", density=0.1),
                injector) if i == 1
            else LocalTransport(server, compress="topk8", density=0.1)),
        num_clients=n_clients)
    runner.clients[1].failure_policy = FailurePolicy.SKIP

    all_losses = []
    for r in range(10):
        all_losses.append(runner.train_round(batches(n_clients, seed=r)))
    c0 = [l[0] for l in all_losses]
    assert all(np.isfinite(l) for l in c0)
    assert np.mean(c0[-3:]) < np.mean(c0[:3])  # still learning
    assert sum(l[1] is None for l in all_losses) == 3


@pytest.mark.slow
def test_multi_client_transformer_lm():
    """Config 3 with the long-context family: two LM clients share one
    server trunk; per-client handshakes and FedAvg'd bottoms work on
    token sequences exactly as on images."""
    from split_learning_tpu.data.datasets import synthetic_lm
    from split_learning_tpu.models.transformer import transformer_plan

    cfg = Config(mode="split", model="transformer_lm", batch_size=BATCH,
                 num_clients=2)
    plan = transformer_plan(lm=True)
    ds = synthetic_lm(seq_len=16, n_train=64)
    sample = ds.train.x[:BATCH]
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), sample)
    runner = MultiClientSplitRunner(
        plan, cfg, jax.random.PRNGKey(0),
        transport_factory=lambda i: LocalTransport(server),
        num_clients=2, sync_bottoms_every=2)
    for r in range(4):
        lo = BATCH * (2 * r) % 48
        losses = runner.train_round([
            (ds.train.x[lo:lo + BATCH], ds.train.y[lo:lo + BATCH]),
            (ds.train.x[lo + BATCH:lo + 2 * BATCH],
             ds.train.y[lo + BATCH:lo + 2 * BATCH]),
        ])
        assert all(np.isfinite(l) for l in losses)
    # after sync_bottoms FedAvg, client bottoms are identical
    flat0 = jax.tree_util.tree_leaves(runner.clients[0].state.params)
    flat1 = jax.tree_util.tree_leaves(runner.clients[1].state.params)
    for a, b in zip(flat0, flat1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


@pytest.mark.slow
def test_weighted_fedavg_by_example_count():
    """Canonical FedAvg weights client updates by example count: the
    aggregated params are the weighted mean, end-to-end through the
    server aggregate op (num_examples on the wire) and directly through
    fedavg_mean; uniform and 1-client behavior are unchanged."""
    import threading

    from split_learning_tpu.models import get_plan
    from split_learning_tpu.runtime import ServerRuntime
    from split_learning_tpu.runtime.state import fedavg_mean
    from split_learning_tpu.utils import Config

    # unit: weighted mean math
    a = {"w": np.ones((2, 2), np.float32)}
    b = {"w": np.full((2, 2), 4.0, np.float32)}
    got = fedavg_mean([a, b], weights=[3, 1])
    np.testing.assert_allclose(np.asarray(got["w"]),
                               np.full((2, 2), 1.75), rtol=1e-6)
    # uniform default unchanged
    got = fedavg_mean([a, b])
    np.testing.assert_allclose(np.asarray(got["w"]),
                               np.full((2, 2), 2.5), rtol=1e-6)
    with pytest.raises(ValueError):
        fedavg_mean([a, b], weights=[1])
    with pytest.raises(ValueError):
        fedavg_mean([a, b], weights=[1, 0])

    # end-to-end: two clients submit with different example counts
    cfg = Config(mode="federated", num_clients=2, batch_size=8)
    plan = get_plan(mode="federated")
    rs = np.random.RandomState(0)
    x = rs.randn(8, 28, 28, 1).astype(np.float32)
    runtime = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x)

    p1 = jax.tree_util.tree_map(lambda l: np.ones_like(l),
                                runtime.state.params)
    p2 = jax.tree_util.tree_map(lambda l: np.full_like(l, 4.0),
                                runtime.state.params)
    results = {}

    def client(name, params, n):
        results[name] = runtime.aggregate(params, 0, 1.0,
                                          {"c1": 1, "c2": 2}[name],
                                          num_examples=n)

    t = threading.Thread(target=client, args=("c1", p1, 300))
    t.start()
    client("c2", p2, 100)
    t.join(timeout=30)
    want = 0.75 * 1.0 + 0.25 * 4.0  # 300:100 weighting
    for res in results.values():
        leaf = jax.tree_util.tree_leaves(res)[0]
        np.testing.assert_allclose(np.asarray(leaf),
                                   np.full_like(np.asarray(leaf), want),
                                   rtol=1e-6)

    # mixed round (one client omits num_examples): uniform fallback —
    # never a raw count averaged against a defaulted weight
    results.clear()
    t = threading.Thread(target=lambda: results.__setitem__(
        "c1", runtime.aggregate(p1, 1, 1.0, 3, num_examples=300)))
    t.start()
    results["c2"] = runtime.aggregate(p2, 1, 1.0, 4)  # no count
    t.join(timeout=30)
    for res in results.values():
        leaf = jax.tree_util.tree_leaves(res)[0]
        np.testing.assert_allclose(np.asarray(leaf),
                                   np.full_like(np.asarray(leaf), 2.5),
                                   rtol=1e-6)

    # invalid count 400s its own client without poisoning the round
    from split_learning_tpu.runtime.server import ProtocolError
    with pytest.raises(ProtocolError):
        runtime.aggregate(p1, 2, 1.0, 5, num_examples=0)
