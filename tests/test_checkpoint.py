"""Joint checkpoint/resume: restoring mid-run must reproduce the exact
continuation (the property a reference pod-restart destroys)."""

import jax
import numpy as np
import pytest

from split_learning_tpu.models import get_plan
from split_learning_tpu.runtime import ServerRuntime, SplitClientTrainer
from split_learning_tpu.runtime.checkpoint import Checkpointer, joint_state
from split_learning_tpu.runtime.fused import FusedSplitTrainer
from split_learning_tpu.transport import LocalTransport
from split_learning_tpu.utils import Config

BATCH = 16


def data(n):
    rs = np.random.RandomState(7)
    return [(rs.randn(BATCH, 28, 28, 1).astype(np.float32),
             rs.randint(0, 10, (BATCH,)).astype(np.int64)) for _ in range(n)]


@pytest.mark.slow
def test_fused_checkpoint_resume(tmp_path):
    plan = get_plan(mode="split")
    cfg = Config(mode="split", batch_size=BATCH)
    batches = data(8)

    tr = FusedSplitTrainer(plan, cfg, jax.random.PRNGKey(0), batches[0][0])
    for x, y in batches[:4]:
        tr.train_step(x, y)

    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    ckpt.save(4, tr.state)

    # continue to the end: this is the ground-truth continuation
    for x, y in batches[4:]:
        tr.train_step(x, y)
    final_a = jax.tree_util.tree_leaves(tr.state.params)

    # fresh trainer, restore at step 4, replay the same tail
    tr2 = FusedSplitTrainer(plan, cfg, jax.random.PRNGKey(123),
                            batches[0][0])  # different init on purpose
    tr2.state = ckpt.restore(template=tr2.state)
    for x, y in batches[4:]:
        tr2.train_step(x, y)
    final_b = jax.tree_util.tree_leaves(tr2.state.params)

    for a, b in zip(final_a, final_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    assert ckpt.latest_step() == 4
    ckpt.close()


def test_joint_mpmd_checkpoint_keeps_halves_in_sync(tmp_path):
    """Both parties restore from ONE checkpoint — a client-only restart
    can no longer silently desync the halves (SURVEY.md §5)."""
    plan = get_plan(mode="split")
    cfg = Config(mode="split", batch_size=BATCH)
    batches = data(6)

    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), batches[0][0])
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server))
    for i, (x, y) in enumerate(batches[:3]):
        client.train_step(x, y, i)

    ckpt = Checkpointer(str(tmp_path / "joint"))
    ckpt.save(3, joint_state(client=client.state, server=server.state,
                             step=3))

    for i, (x, y) in enumerate(batches[3:], start=3):
        client.train_step(x, y, i)
    truth = jax.tree_util.tree_leaves(
        (client.state.params, server.state.params))

    # "restart" both parties from the joint checkpoint
    server2 = ServerRuntime(plan, cfg, jax.random.PRNGKey(9), batches[0][0])
    client2 = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(9),
                                 LocalTransport(server2))
    client2.ensure_init(batches[0][0])
    restored = ckpt.restore(template=joint_state(
        client=client2.state, server=server2.state, step=0))
    client2.state = restored["client"]
    server2.resume_from(restored["server"], restored["step"])
    for i, (x, y) in enumerate(batches[3:], start=3):
        client2.train_step(x, y, i)
    got = jax.tree_util.tree_leaves(
        (client2.state.params, server2.state.params))
    for a, b in zip(truth, got):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    ckpt.close()


def test_save_is_async_and_reads_barrier(tmp_path, monkeypatch):
    """Round-1 VERDICT weak #6 regression: save() must enqueue without
    waiting (the blocking predecessor stalled every client under the
    server lock on checkpoint steps), while every read path and close()
    must barrier on in-flight writes. Pinned at the manager seam so the
    contract holds regardless of disk speed."""
    import jax.numpy as jnp
    from split_learning_tpu.runtime.checkpoint import Checkpointer

    ckpt = Checkpointer(str(tmp_path / "async"))
    calls = []
    orig_wait = ckpt._mgr.wait_until_finished
    orig_save = ckpt._mgr.save
    monkeypatch.setattr(
        ckpt._mgr, "wait_until_finished",
        lambda: (calls.append("wait"), orig_wait())[1])

    def save(*a, **kw):
        calls.append("save_enter")
        out = orig_save(*a, **kw)
        calls.append("save_exit")
        return out

    monkeypatch.setattr(ckpt._mgr, "save", save)

    ckpt.save(1, {"w": jnp.ones((8,))})
    # orbax's save may internally barrier on the PREVIOUS write (that is
    # pipelining, fine); the regression was OUR save barriering on its own
    # write — i.e. a "wait" AFTER the enqueue returns
    assert "save_exit" in calls
    assert "wait" not in calls[calls.index("save_exit") + 1:], \
        "save() must not block on its own write"

    calls.clear()
    assert ckpt.latest_step() == 1
    assert "wait" in calls, "latest_step() must barrier first"

    calls.clear()
    restored = ckpt.restore_raw(step=1)
    np.testing.assert_allclose(np.asarray(restored["w"]), np.ones(8))
    assert "wait" in calls, "restore must barrier first"

    calls.clear()
    ckpt.close()
    assert "wait" in calls, "close() must drain outstanding writes"


def test_ckpt_drain_barriers_on_every_exit_path(tmp_path, monkeypatch):
    """PR 12 shutdown regression: the CLI train/serve paths wrap their
    run loops in ``_ckpt_drain``, so an in-flight async save is drained
    before process exit EVEN when the loop raises — a slow write must
    never be torn by interpreter teardown. Pinned at the manager seam
    with a slow-save stub so the contract holds regardless of disk
    speed."""
    import time as _time

    import jax.numpy as jnp

    from split_learning_tpu.launch.run import _ckpt_drain

    ckpt = Checkpointer(str(tmp_path / "slow"))
    calls = []
    orig_wait = ckpt._mgr.wait_until_finished
    monkeypatch.setattr(
        ckpt._mgr, "wait_until_finished",
        lambda: (calls.append("wait"), orig_wait())[1])
    orig_save = ckpt._mgr.save

    def slow_save(*a, **kw):
        _time.sleep(0.05)  # the write is still in flight at teardown
        return orig_save(*a, **kw)

    monkeypatch.setattr(ckpt._mgr, "save", slow_save)

    with pytest.raises(RuntimeError, match="mid-epoch"):
        with _ckpt_drain(ckpt):
            ckpt.save(1, {"w": jnp.ones((4,))})
            raise RuntimeError("mid-epoch failure")
    assert "wait" in calls, "error exit must drain in-flight saves"

    calls.clear()
    with _ckpt_drain(ckpt):
        ckpt.save(2, {"w": jnp.zeros((4,))})
    assert "wait" in calls, "clean exit must drain in-flight saves"
    assert ckpt.latest_step() == 2
    ckpt.close()

    with _ckpt_drain(None):  # serve/train without --ckpt-dir: a no-op
        pass


def test_resume_restores_replay_cache_from_extras(tmp_path):
    """PR 12 satellite: a resume whose checkpoint carries the runtime
    extras sidecar restores the replay cache — a client retrying its
    in-flight step against the recovered server gets the pre-crash
    reply byte-for-byte. A stale or missing sidecar falls back to the
    PR 4 semantics (clear)."""
    import jax
    import jax.numpy as jnp

    from split_learning_tpu.runtime.checkpoint import (read_latest_extras,
                                                       write_extras)

    cfg = Config(mode="split", batch_size=8)
    plan = get_plan(mode="split")
    rs = np.random.RandomState(0)
    x = rs.randn(8, 28, 28, 1).astype(np.float32)
    y = rs.randint(0, 10, (8,)).astype(np.int64)
    rt = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x)
    acts = np.asarray(plan.stages[0].apply(
        plan.init(jax.random.PRNGKey(0), jnp.asarray(x))[0],
        jnp.asarray(x)))
    grads, loss = rt.split_step(acts, y, 0)
    rt.attach_reply_body(0, "split_step", 0, b"\x01wire-reply")
    state = rt.export_state()
    payload = rt.export_runtime_extras(0)

    ckdir = tmp_path / "extras"
    ckdir.mkdir()
    write_extras(str(ckdir), payload)

    # restart with a matching sidecar: the duplicate is served from the
    # restored cache, bit-identical, without touching the model
    rt2 = ServerRuntime(plan, cfg, jax.random.PRNGKey(1), x)
    rt2.resume_from(state, 0, extras=read_latest_extras(str(ckdir), step=0))
    body, _ = rt2.replay_lookup(0, "split_step", 0)
    assert body == b"\x01wire-reply"

    # stale sidecar (step mismatch): rejected, cache cleared
    rt3 = ServerRuntime(plan, cfg, jax.random.PRNGKey(2), x)
    rt3.resume_from(state, 5, extras=read_latest_extras(str(ckdir)))
    assert rt3.replay_lookup(0, "split_step", 0) == (None, None)

    # no sidecar at all: same clear fallback
    rt4 = ServerRuntime(plan, cfg, jax.random.PRNGKey(3), x)
    rt4.resume_from(state, 0)
    assert rt4.replay_lookup(0, "split_step", 0) == (None, None)


def test_restore_partial_preserves_optimizer_types(tmp_path):
    """The server half of a JOINT checkpoint must restore TYPED (optax
    TraceState namedtuples intact): a raw restore decays opt_state to
    dicts that a live momentum optimizer cannot update."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from split_learning_tpu.models import get_plan
    from split_learning_tpu.runtime import ServerRuntime
    from split_learning_tpu.runtime.checkpoint import (Checkpointer,
                                                       joint_state)
    from split_learning_tpu.utils import Config

    cfg = Config(mode="split", batch_size=8, momentum=0.9)
    plan = get_plan(mode="split")
    rs = np.random.RandomState(0)
    x = rs.randn(8, 28, 28, 1).astype(np.float32)
    y = rs.randint(0, 10, (8,)).astype(np.int64)
    rt = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x)
    acts = np.asarray(plan.stages[0].apply(
        plan.init(jax.random.PRNGKey(0), jnp.asarray(x))[0],
        jnp.asarray(x)))
    rt.split_step(acts, y, 0)
    ref = np.array(jax.tree_util.tree_leaves(rt.state.params)[0])
    ck = Checkpointer(str(tmp_path / "joint"))
    ck.save(1, joint_state(client={"params": 0}, server=rt.state))
    ck.close()

    ck2 = Checkpointer(str(tmp_path / "joint"))  # fresh manager (restart)
    rt2 = ServerRuntime(plan, cfg, jax.random.PRNGKey(1), x)
    tree = ck2.restore_partial({"server": rt2.state})
    got = np.array(jax.tree_util.tree_leaves(tree["server"].params)[0])
    np.testing.assert_array_equal(ref, got)
    rt2.resume_from(tree["server"], 1)
    # the real assertion: a momentum update over the restored opt_state
    _, loss = rt2.split_step(acts, y, 2)
    assert np.isfinite(loss)
    # missing subtree is a loud error, not a silent fresh init
    import pytest as _pytest
    with _pytest.raises(KeyError):
        ck2.restore_partial({"nonexistent": rt2.state})
    ck2.close()
