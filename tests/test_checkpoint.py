"""Joint checkpoint/resume: restoring mid-run must reproduce the exact
continuation (the property a reference pod-restart destroys)."""

import jax
import numpy as np

from split_learning_tpu.models import get_plan
from split_learning_tpu.runtime import ServerRuntime, SplitClientTrainer
from split_learning_tpu.runtime.checkpoint import Checkpointer, joint_state
from split_learning_tpu.runtime.fused import FusedSplitTrainer
from split_learning_tpu.transport import LocalTransport
from split_learning_tpu.utils import Config

BATCH = 16


def data(n):
    rs = np.random.RandomState(7)
    return [(rs.randn(BATCH, 28, 28, 1).astype(np.float32),
             rs.randint(0, 10, (BATCH,)).astype(np.int64)) for _ in range(n)]


def test_fused_checkpoint_resume(tmp_path):
    plan = get_plan(mode="split")
    cfg = Config(mode="split", batch_size=BATCH)
    batches = data(8)

    tr = FusedSplitTrainer(plan, cfg, jax.random.PRNGKey(0), batches[0][0])
    for x, y in batches[:4]:
        tr.train_step(x, y)

    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    ckpt.save(4, tr.state)

    # continue to the end: this is the ground-truth continuation
    for x, y in batches[4:]:
        tr.train_step(x, y)
    final_a = jax.tree_util.tree_leaves(tr.state.params)

    # fresh trainer, restore at step 4, replay the same tail
    tr2 = FusedSplitTrainer(plan, cfg, jax.random.PRNGKey(123),
                            batches[0][0])  # different init on purpose
    tr2.state = ckpt.restore(template=tr2.state)
    for x, y in batches[4:]:
        tr2.train_step(x, y)
    final_b = jax.tree_util.tree_leaves(tr2.state.params)

    for a, b in zip(final_a, final_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    assert ckpt.latest_step() == 4
    ckpt.close()


def test_joint_mpmd_checkpoint_keeps_halves_in_sync(tmp_path):
    """Both parties restore from ONE checkpoint — a client-only restart
    can no longer silently desync the halves (SURVEY.md §5)."""
    plan = get_plan(mode="split")
    cfg = Config(mode="split", batch_size=BATCH)
    batches = data(6)

    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), batches[0][0])
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server))
    for i, (x, y) in enumerate(batches[:3]):
        client.train_step(x, y, i)

    ckpt = Checkpointer(str(tmp_path / "joint"))
    ckpt.save(3, joint_state(client=client.state, server=server.state,
                             step=3))

    for i, (x, y) in enumerate(batches[3:], start=3):
        client.train_step(x, y, i)
    truth = jax.tree_util.tree_leaves(
        (client.state.params, server.state.params))

    # "restart" both parties from the joint checkpoint
    server2 = ServerRuntime(plan, cfg, jax.random.PRNGKey(9), batches[0][0])
    client2 = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(9),
                                 LocalTransport(server2))
    client2.ensure_init(batches[0][0])
    restored = ckpt.restore(template=joint_state(
        client=client2.state, server=server2.state, step=0))
    client2.state = restored["client"]
    server2.resume_from(restored["server"], restored["step"])
    for i, (x, y) in enumerate(batches[3:], start=3):
        client2.train_step(x, y, i)
    got = jax.tree_util.tree_leaves(
        (client2.state.params, server2.state.params))
    for a, b in zip(truth, got):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    ckpt.close()
