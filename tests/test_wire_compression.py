"""topk8 wire mode end-to-end: the none-path pin (--compress none must be
bit-for-bit the legacy wire), error-feedback semantics (rollback on a lost
POST, no rollback in-process), the bitmap/index encoding switch, and the
compression-ratio accounting surfaced on /metrics."""

import math

import jax
import numpy as np
import pytest
import requests

from split_learning_tpu.models import get_plan
from split_learning_tpu.runtime import ServerRuntime, SplitClientTrainer
from split_learning_tpu.transport import LocalTransport, TransportError
from split_learning_tpu.transport.http import HttpTransport, SplitHTTPServer
from split_learning_tpu.transport import codec
from split_learning_tpu.utils import Config

BATCH = 8


def make_server(seed=0):
    cfg = Config(mode="split", batch_size=BATCH)
    plan = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    return plan, cfg, ServerRuntime(plan, cfg, jax.random.PRNGKey(seed),
                                    sample)


def train_steps(plan, cfg, transport, n, seed=1):
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0), transport)
    rs = np.random.RandomState(seed)
    losses = []
    for step in range(n):
        x = rs.randn(BATCH, 28, 28, 1).astype(np.float32)
        y = rs.randint(0, 10, (BATCH,)).astype(np.int64)
        losses.append(client.train_step(x, y, step))
    return client, losses


# --------------------------------------------------------------------- #
# the none pin: adding the compression layer must not move a single bit
# of the uncompressed path
# --------------------------------------------------------------------- #
def test_local_compress_none_matches_legacy_bitwise():
    """LocalTransport(compress=None) is the legacy direct path;
    compress="none" adds the full wire emulation — the step math must be
    bit-for-bit identical between them."""
    plan, cfg, rt_a = make_server()
    _, _, rt_b = make_server()
    _, losses_a = train_steps(plan, cfg, LocalTransport(rt_a), 6)
    client_b, losses_b = train_steps(
        plan, cfg, LocalTransport(rt_b, compress="none"), 6)
    assert losses_a == losses_b  # float equality: identical trajectories
    for la, lb in zip(jax.tree_util.tree_leaves(rt_a.state.params),
                      jax.tree_util.tree_leaves(rt_b.state.params)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_http_compress_none_payload_unchanged():
    """With --compress none the POSTed tree must carry no compress/
    density keys and raw float32 activations — the wire format of every
    previous release, pinned."""
    plan, cfg, runtime = make_server()
    server = SplitHTTPServer(runtime).start()
    transport = HttpTransport(server.url)  # compress defaults to "none"
    sent = []
    orig = transport._session.post

    def capture(url, data=None, **kw):
        sent.append(codec.decode(data))
        return orig(url, data=data, **kw)

    transport._session.post = capture
    try:
        train_steps(plan, cfg, transport, 2)
    finally:
        transport.close()
        server.stop()
    assert sent
    for tree in sent:
        assert "compress" not in tree and "density" not in tree
        acts = tree["activations"]
        assert isinstance(acts, np.ndarray) and acts.dtype == np.float32
    assert transport.stats.summary().get("compression_ratio") is None


# --------------------------------------------------------------------- #
# codec wire format: encoding switch + error-feedback state machine
# --------------------------------------------------------------------- #
def test_bitmap_vs_index_encoding_switch():
    """density 0.1 -> packed bitmask (n/8 B < 4k B); density < 1/32 ->
    int32 indices win. Both must round-trip exactly."""
    rs = np.random.RandomState(0)
    a = rs.randn(64, 64).astype(np.float32)
    dense, _ = codec.topk8_compress(a, 0.1)
    assert "m" in dense and "idx" not in dense
    sparse, _ = codec.topk8_compress(a, 0.01)
    assert "idx" in sparse and "m" not in sparse
    for packed, density in ((dense, 0.1), (sparse, 0.01)):
        out = codec.decompress_tree(codec.decode(codec.encode(packed)))
        assert out.shape == a.shape and out.dtype == a.dtype
        k = math.ceil(density * a.size)
        assert int(np.count_nonzero(out)) <= k


def test_topk8_wire_is_smaller_than_q8():
    a = np.random.RandomState(1).randn(64, 26, 26, 32).astype(np.float32)
    raw = len(codec.encode({"x": a}))
    q8 = len(codec.encode({"x": codec.q8_compress(a)}))
    tk = len(codec.encode({"x": codec.topk8_compress(a, 0.1)[0]}))
    assert raw / tk >= 8.0
    assert q8 / tk >= 2.5


def test_ef_rollback_restores_state():
    """compress -> rollback -> compress must equal a fresh compress (the
    failed send never happened); without rollback the residual feeds the
    next selection and the packed tensors differ."""
    rs = np.random.RandomState(2)
    a = rs.randn(32, 32).astype(np.float32)
    ef = codec.TopK8EF()
    p1 = ef.compress("k", a, 0.1)
    ef.rollback("k")
    p2 = ef.compress("k", a, 0.1)
    np.testing.assert_array_equal(p1["q"], p2["q"])
    np.testing.assert_array_equal(p1["m"], p2["m"])
    assert p1["scale"] == p2["scale"]
    p3 = ef.compress("k", a, 0.1)  # no rollback: residual now in play
    assert (not np.array_equal(p2["q"], p3["q"])
            or not np.array_equal(p2["m"], p3["m"]))


def test_ef_residual_reduces_two_step_error():
    """The point of error feedback: over two steps on the same input,
    shipped mass accumulates — reconstruction error after step 2 is
    strictly below the stateless single-shot error."""
    rs = np.random.RandomState(3)
    a = rs.randn(64, 64).astype(np.float32)
    stateless, _ = codec.topk8_compress(a, 0.05)
    err0 = float(np.linalg.norm(a - codec.topk8_decompress(stateless)))
    ef = codec.TopK8EF()
    d1 = codec.topk8_decompress(ef.compress("k", a, 0.05))
    d2 = codec.topk8_decompress(ef.compress("k", a, 0.05))
    err_ef = float(np.linalg.norm(2 * a - (d1 + d2))) / 2
    assert err_ef < err0
    # and the second step ships mass the first one dropped, instead of
    # re-sending the same top coordinates forever (the stateless failure
    # mode EF exists to fix)
    nz1 = set(np.flatnonzero(d1.reshape(-1)))
    nz2 = set(np.flatnonzero(d2.reshape(-1)))
    assert len(nz2 - nz1) > len(nz1) // 2


def test_http_transport_rolls_back_ef_on_failed_post():
    """A POST that never reached the server must not leave the shipped
    mass marked delivered: the client's EF buffer for that role is
    restored to its pre-call state."""
    transport = HttpTransport("http://127.0.0.1:9", timeout=0.2,
                              compress="topk8", density=0.1)
    rs = np.random.RandomState(4)
    acts = rs.randn(BATCH, 26, 26, 32).astype(np.float32)
    labels = rs.randint(0, 10, (BATCH,)).astype(np.int64)
    with pytest.raises(TransportError):
        transport.split_step(acts, labels, 0)
    assert transport._ef._res.get("acts") is None  # rolled back to fresh
    transport.close()


# --------------------------------------------------------------------- #
# end-to-end: training through the compressed wire
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("mode", ["int8", "topk8"])
def test_local_wire_emulation_trains(mode):
    plan, cfg, runtime = make_server()
    transport = LocalTransport(runtime, compress=mode, density=0.1)
    _, losses = train_steps(plan, cfg, transport, 12)
    assert all(np.isfinite(l) for l in losses)
    assert np.mean(losses[-3:]) < np.mean(losses[:3])
    s = transport.stats.summary()
    if mode == "topk8":
        assert s["compression_ratio"] > 8.0
    else:
        assert s["compression_ratio"] > 3.5


def test_http_topk8_end_to_end_with_metrics_gauge():
    """Full loopback run with both parties in topk8 mode: training
    converges, the client records its ratio, and the server publishes
    wire_compression_ratio on /metrics."""
    plan, cfg, runtime = make_server()
    server = SplitHTTPServer(runtime, compress="topk8",
                             density=0.1).start()
    transport = HttpTransport(server.url, compress="topk8", density=0.1)
    try:
        _, losses = train_steps(plan, cfg, transport, 8)
        assert all(np.isfinite(l) for l in losses)
        s = transport.stats.summary()
        assert s["compression_ratio"] > 8.0
        body = requests.get(f"{server.url}/metrics", timeout=10).text
        line = [l for l in body.splitlines()
                if l.startswith("slt_wire_compression_ratio")]
        assert line, body
        assert float(line[0].split()[-1]) > 8.0
    finally:
        transport.close()
        server.stop()


def test_http_server_honors_client_requested_mode():
    """The request's compress key overrides the server default, so a
    dense client against a topk8-default server still gets dense replies
    (and vice versa) — mixed fleets stay correct."""
    plan, cfg, runtime = make_server()
    server = SplitHTTPServer(runtime, compress="topk8",
                             density=0.1).start()
    dense = HttpTransport(server.url)  # compress="none"
    try:
        _, losses = train_steps(plan, cfg, dense, 3)
        assert all(np.isfinite(l) for l in losses)
        # no compressed leaves travelled in either direction
        assert dense.stats.summary().get("compression_ratio") is None
    finally:
        dense.close()
        server.stop()
