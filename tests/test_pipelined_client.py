"""Pipelined split client (runtime/pipelined_client.py).

The contract has three parts: depth=1 is EXACTLY the synchronous loop
(same math as monolithic — the equivalence property extends); depth>1 is
bounded-staleness async SGD that still converges; and the HTTP form really
runs W lanes concurrently against a strict_steps=False server.
"""

import numpy as np
import pytest

import jax

from split_learning_tpu.models import get_plan
from split_learning_tpu.runtime import (
    PipelinedSplitClientTrainer, ServerRuntime, SplitClientTrainer)
from split_learning_tpu.transport import LocalTransport
from split_learning_tpu.utils import Config

SEED = 42
BATCH = 16


def _batches(n_steps, seed=123):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n_steps):
        out.append((rs.randn(BATCH, 28, 28, 1).astype(np.float32),
                    rs.randint(0, 10, (BATCH,)).astype(np.int64)))
    return out


def _learnable_batches(n_steps, seed=7):
    """Class-conditional data so convergence is measurable."""
    rs = np.random.RandomState(seed)
    centers = rs.randn(10, 28 * 28).astype(np.float32)
    out = []
    for _ in range(n_steps):
        y = rs.randint(0, 10, (BATCH,)).astype(np.int64)
        x = centers[y] + 0.5 * rs.randn(BATCH, 28 * 28).astype(np.float32)
        out.append((x.reshape(BATCH, 28, 28, 1), y))
    return out


def test_depth1_equals_synchronous_loop():
    batches = _batches(8)
    cfg = Config(mode="split", batch_size=BATCH, lr=0.01)
    plan = get_plan(mode="split")

    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(SEED), batches[0][0])
    sync = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(SEED),
                              LocalTransport(server))
    sync_losses = [sync.train_step(x, y, i) for i, (x, y) in enumerate(batches)]

    server2 = ServerRuntime(plan, cfg, jax.random.PRNGKey(SEED), batches[0][0])
    piped = PipelinedSplitClientTrainer(
        plan, cfg, jax.random.PRNGKey(SEED), LocalTransport(server2), depth=1)
    records = piped.train(lambda: iter(batches), epochs=1)
    piped.close()

    np.testing.assert_allclose([r.loss for r in records], sync_losses,
                               rtol=1e-6, atol=1e-7)
    for a, b in zip(jax.tree_util.tree_leaves(piped.state.params),
                    jax.tree_util.tree_leaves(sync.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


@pytest.mark.slow
@pytest.mark.parametrize("depth", [2, 4])
def test_bounded_staleness_converges(depth):
    """Async SGD with delay < depth still learns the learnable task, and
    every step is processed exactly once (records cover the range)."""
    batches = _learnable_batches(60)
    cfg = Config(mode="split", batch_size=BATCH, lr=0.01)
    plan = get_plan(mode="split")
    # out-of-order arrival is part of the deal: strict_steps off
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(SEED),
                           batches[0][0], strict_steps=False)
    piped = PipelinedSplitClientTrainer(
        plan, cfg, jax.random.PRNGKey(SEED), LocalTransport(server),
        depth=depth)
    records = piped.train(lambda: iter(batches), epochs=1)
    piped.close()

    assert sorted(r.step for r in records) == list(range(60))
    losses = [r.loss for r in records]
    assert all(np.isfinite(losses))
    assert np.mean(losses[-10:]) < 0.5 * np.mean(losses[:10]), (
        losses[:10], losses[-10:])


@pytest.mark.slow
def test_http_lanes_run_concurrently():
    """W HttpTransport lanes against one strict_steps=False HTTP server:
    all steps complete, loss finite, and the server saw every step."""
    from split_learning_tpu.transport.http import (
        HttpTransport, SplitHTTPServer)

    batches = _learnable_batches(20)
    cfg = Config(mode="split", batch_size=BATCH, lr=0.01)
    plan = get_plan(mode="split")
    runtime = ServerRuntime(plan, cfg, jax.random.PRNGKey(SEED),
                            batches[0][0], strict_steps=False)
    server = SplitHTTPServer(runtime).start()
    piped = PipelinedSplitClientTrainer(
        plan, cfg, jax.random.PRNGKey(SEED), HttpTransport(server.url),
        depth=4, transport_factory=lambda: HttpTransport(server.url))
    try:
        records = piped.train(lambda: iter(batches), epochs=1)
    finally:
        piped.close()
        server.stop()
    # every step returned a loss, which requires a server half-step each —
    # the wire-level proof all 20 exchanges completed
    assert sorted(r.step for r in records) == list(range(20))
    assert all(np.isfinite(r.loss) for r in records)
    # acknowledged step never regresses under out-of-order arrival
    assert runtime._last_step[0] == 19


def test_fault_mid_window_raises_and_quiesces():
    """A transport fault inside the in-flight window surfaces as an
    exception from train() (the documented RAISE policy) instead of
    hanging a lane thread, and close() returns promptly afterward."""
    from split_learning_tpu.transport.base import (
        FaultInjector, FaultyTransport, TransportError)

    batches = _batches(12)
    cfg = Config(mode="split", batch_size=BATCH, lr=0.01)
    plan = get_plan(mode="split")
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(SEED),
                           batches[0][0], strict_steps=False)
    faulty = FaultyTransport(LocalTransport(server),
                             FaultInjector(fail_steps={5}))
    piped = PipelinedSplitClientTrainer(
        plan, cfg, jax.random.PRNGKey(SEED), faulty, depth=3)
    with pytest.raises(TransportError, match="injected fault"):
        piped.train(lambda: iter(batches), epochs=1)
    piped.close()  # must join lanes without hanging


@pytest.mark.slow
def test_checkpoint_cli_resume_with_depth(tmp_path, capsys):
    """--pipeline-depth composes with checkpoint/resume: the window
    drains at each epoch boundary, so the saved joint state is quiesced
    and a resumed run continues from it."""
    from split_learning_tpu.launch.run import main

    args = ["train", "--mode", "split", "--transport", "local",
            "--dataset", "synthetic", "--batch-size", "16",
            "--epochs", "1", "--steps", "8", "--pipeline-depth", "3",
            "--data-dir", str(tmp_path / "data"), "--tracking", "noop",
            "--checkpoint-dir", str(tmp_path / "ckpt")]
    assert main(args) == 0
    assert main(args + ["--resume"]) == 0
    err = capsys.readouterr().err
    assert "[ckpt] resumed at step" in err, err[-800:]


def test_depth_validation():
    plan = get_plan(mode="split")
    cfg = Config(mode="split", batch_size=BATCH)
    with pytest.raises(ValueError, match="depth"):
        PipelinedSplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                    LocalTransport(None), depth=0)
