"""Optimizer factory (runtime/state.py make_tx / make_lr).

The reference trains everything with SGD(lr=0.01)
(``src/client_part.py:17``, ``src/server_part.py:15``); that stays the
default, bit-for-bit. The transformer/causal-LM families added beyond
the reference's scope get the standard recipe — adam/adamw with
decoupled weight decay and warmup/cosine schedules — through the same
single construction site every trainer shares.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from split_learning_tpu.runtime.state import (
    apply_grads, make_lr, make_state, make_tx, sgd)
from split_learning_tpu.utils import Config


def toy_tree():
    return {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones((3,))}


def test_default_config_is_reference_sgd_exactly():
    """make_tx(Config()) must reproduce the reference optimizer's update
    bit-for-bit — the parity guarantees rest on it."""
    params = toy_tree()
    grads = jax.tree_util.tree_map(lambda x: 0.1 * x + 0.5, params)
    want = apply_grads(sgd(0.01), make_state(params, sgd(0.01)), grads)
    got = apply_grads(make_tx(Config()), make_state(params, make_tx(Config())),
                      grads)
    for a, b in zip(jax.tree_util.tree_leaves(want.params),
                    jax.tree_util.tree_leaves(got.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_make_lr_warmup_then_constant():
    cfg = Config(warmup_steps=10)
    lr = make_lr(cfg)
    assert float(lr(0)) == 0.0
    assert np.isclose(float(lr(5)), cfg.lr / 2)
    assert np.isclose(float(lr(10)), cfg.lr)
    assert np.isclose(float(lr(1000)), cfg.lr)


def test_make_lr_warmup_cosine():
    cfg = Config(warmup_steps=10, decay_steps=110)
    lr = make_lr(cfg)
    assert float(lr(0)) == 0.0
    assert np.isclose(float(lr(10)), cfg.lr)
    mid = float(lr(60))  # halfway through the cosine leg
    assert np.isclose(mid, cfg.lr / 2, rtol=1e-3)
    assert float(lr(110)) <= 1e-9
    # constant default stays a plain float (no schedule state)
    assert make_lr(Config()) == Config().lr


def test_adamw_decoupled_decay_moves_params_without_gradient():
    cfg = Config(optimizer="adamw", weight_decay=0.1, lr=0.1)
    tx = make_tx(cfg)
    params = toy_tree()
    state = make_state(params, tx)
    zero = jax.tree_util.tree_map(jnp.zeros_like, params)
    new = apply_grads(tx, state, zero)
    # decoupled decay shrinks weights even at zero gradient
    assert float(jnp.abs(new.params["w"]).sum()) \
        < float(jnp.abs(params["w"]).sum())


def test_sgd_weight_decay_is_coupled_l2():
    cfg = Config(optimizer="sgd", weight_decay=0.5, lr=0.1)
    tx = make_tx(cfg)
    params = toy_tree()
    state = make_state(params, tx)
    zero = jax.tree_util.tree_map(jnp.zeros_like, params)
    new = apply_grads(tx, state, zero)
    # update = -lr * wd * w
    np.testing.assert_allclose(np.asarray(new.params["w"]),
                               np.asarray(params["w"]) * (1 - 0.1 * 0.5),
                               rtol=1e-6)


def test_config_rejects_bad_optimizer_combos():
    with pytest.raises(ValueError, match="Unknown optimizer"):
        Config(optimizer="lamb")
    with pytest.raises(ValueError, match="adamw"):
        Config(optimizer="adam", weight_decay=0.1)
    with pytest.raises(ValueError, match="decay_steps"):
        Config(warmup_steps=100, decay_steps=50)
    with pytest.raises(ValueError, match="non-negative"):
        Config(weight_decay=-1.0)


def test_optimizer_env_parsing():
    cfg = Config.from_env(env={"SLT_OPTIMIZER": "adamw",
                               "SLT_WEIGHT_DECAY": "0.05",
                               "SLT_WARMUP_STEPS": "7",
                               "SLT_DECAY_STEPS": "70"})
    assert cfg.optimizer == "adamw"
    assert cfg.weight_decay == 0.05
    assert cfg.warmup_steps == 7
    assert cfg.decay_steps == 70


@pytest.mark.slow
def test_fused_trainer_adamw_learns_and_differs_from_sgd():
    """The fused trainer accepts the new optimizers end-to-end: adamw
    with warmup reduces the loss and takes a different trajectory from
    the reference SGD default."""
    from split_learning_tpu.models import get_plan
    from split_learning_tpu.runtime.fused import FusedSplitTrainer

    rs = np.random.RandomState(3)
    # one batch repeated: the trajectory must descend on data it has
    # seen, which keeps the assertion sharp at toy scale
    xb = rs.randn(16, 28, 28, 1).astype(np.float32)
    yb = rs.randint(0, 10, (16,)).astype(np.int64)

    def run(cfg):
        plan = get_plan(mode="split")
        tr = FusedSplitTrainer(plan, cfg, jax.random.PRNGKey(0), xb)
        return [tr.train_step(xb, yb) for _ in range(10)]

    adamw = run(Config(optimizer="adamw", lr=1e-3, weight_decay=0.01,
                       warmup_steps=2, batch_size=16))
    sgd_l = run(Config(batch_size=16))
    assert np.mean(adamw[-3:]) < adamw[0]
    assert not np.allclose(adamw, sgd_l)


@pytest.mark.slow
def test_pallas_kernels_with_adamw_fall_back_to_optax_update():
    """kernels='pallas' + a non-SGD optimizer: the loss kernel stays
    pallas but the update runs optax — and still learns."""
    from split_learning_tpu.models import get_plan
    from split_learning_tpu.runtime.fused import FusedSplitTrainer

    rs = np.random.RandomState(4)
    xb = rs.randn(16, 28, 28, 1).astype(np.float32)
    yb = rs.randint(0, 10, (16,)).astype(np.int64)
    cfg = Config(optimizer="adamw", lr=1e-3, kernels="pallas",
                 batch_size=16)
    tr = FusedSplitTrainer(get_plan(mode="split"), cfg,
                           jax.random.PRNGKey(0), xb)
    losses = [float(tr.train_step(xb, yb)) for _ in range(10)]
    assert np.mean(losses[-3:]) < losses[0]
    # optax adam state, not the pallas momentum trace
    assert tr.state.opt_state != ()


def test_momentum_rejected_off_sgd_and_env_parses():
    with pytest.raises(ValueError, match="momentum"):
        Config(optimizer="adamw", momentum=0.9)
    assert Config.from_env(env={"SLT_MOMENTUM": "0.9"}).momentum == 0.9


def test_grad_clip_global_norm():
    cfg = Config(grad_clip_norm=1.0, lr=1.0)
    tx = make_tx(cfg)
    params = toy_tree()
    state = make_state(params, tx)
    big = jax.tree_util.tree_map(lambda x: 100.0 * jnp.ones_like(x), params)
    new = apply_grads(tx, state, big)
    delta = jax.tree_util.tree_map(lambda a, b: np.asarray(a) - np.asarray(b),
                                   new.params, params)
    norm = np.sqrt(sum(float((d ** 2).sum())
                       for d in jax.tree_util.tree_leaves(delta)))
    # update = -lr * clipped grad, so its norm is exactly the clip
    assert np.isclose(norm, 1.0, rtol=1e-5)
    with pytest.raises(ValueError, match="non-negative"):
        Config(grad_clip_norm=-0.5)


@pytest.mark.slow
def test_schedule_position_survives_fused_resume(tmp_path):
    """Warmup/cosine schedules ride optax's step count inside opt_state:
    a checkpoint/resume at step k must continue the schedule from k, not
    restart warmup — the resumed trajectory equals the uninterrupted one
    step for step."""
    import numpy as np

    from split_learning_tpu.models import get_plan
    from split_learning_tpu.runtime.checkpoint import Checkpointer
    from split_learning_tpu.runtime.fused import FusedSplitTrainer

    rs = np.random.RandomState(9)
    xs = rs.randn(8, 16, 28, 28, 1).astype(np.float32)
    ys = rs.randint(0, 10, (8, 16)).astype(np.int64)
    cfg = Config(optimizer="adamw", lr=5e-3, warmup_steps=3,
                 decay_steps=8, batch_size=16)

    def trainer():
        return FusedSplitTrainer(get_plan(mode="split"), cfg,
                                 jax.random.PRNGKey(0), xs[0])

    # uninterrupted reference
    ref = trainer()
    ref_losses = [ref.train_step(x, y) for x, y in zip(xs, ys)]

    # train 4 steps, checkpoint, resume in a FRESH trainer, finish
    a = trainer()
    for x, y in zip(xs[:4], ys[:4]):
        a.train_step(x, y)
    ck = Checkpointer(str(tmp_path / "ck"))
    ck.save(4, {"trainer": a.state})
    ck.close()

    b = trainer()
    ck2 = Checkpointer(str(tmp_path / "ck"))
    b.state = ck2.restore({"trainer": b.state})["trainer"]
    ck2.close()
    resumed = [b.train_step(x, y) for x, y in zip(xs[4:], ys[4:])]
    np.testing.assert_allclose(resumed, ref_losses[4:], rtol=1e-6,
                               atol=1e-7)
