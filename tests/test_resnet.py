"""ResNet-18/CIFAR-10 (BASELINE.md config 4): shapes, param count, stage
cuts, and the 4-stage GPipe pipeline vs monolithic equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from split_learning_tpu.models import get_plan
from split_learning_tpu.parallel import make_mesh
from split_learning_tpu.parallel.pipeline import PipelinedTrainer
from split_learning_tpu.runtime.fused import FusedSplitTrainer
from split_learning_tpu.utils import Config

BATCH = 8


def n_params(tree):
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def cifar_batch(seed=0):
    rs = np.random.RandomState(seed)
    return (rs.randn(BATCH, 32, 32, 3).astype(np.float32),
            rs.randint(0, 10, (BATCH,)).astype(np.int64))


@pytest.mark.slow
def test_resnet18_shapes_and_params(rng):
    x, _ = cifar_batch()
    plan = get_plan(model="resnet18", mode="split")
    assert plan.num_stages == 2
    params = plan.init(rng, x)
    acts = plan.stages[0].apply(params[0], x)
    assert acts.shape == (BATCH, 32, 32, 64)  # cut after layer1, stride 1
    logits = plan.apply(params, x)
    assert logits.shape == (BATCH, 10)
    # ResNet-18 (GN, CIFAR stem): ~11.2M params
    total = n_params(params)
    assert 10_500_000 < total < 11_400_000


@pytest.mark.slow
def test_resnet18_stage_variants(rng):
    x, _ = cifar_batch()
    plan3 = get_plan(model="resnet18", mode="u_split")
    assert plan3.owners == ("client", "server", "client")
    plan4 = get_plan(model="resnet18_4stage", mode="split")
    assert plan4.num_stages == 4
    params = plan4.init(rng, x)
    shapes = []
    h = x
    for stage, p in zip(plan4.stages, params):
        h = stage.apply(p, h)
        shapes.append(h.shape)
    assert shapes == [(BATCH, 32, 32, 64), (BATCH, 16, 16, 128),
                      (BATCH, 8, 8, 256), (BATCH, 10)]
    with pytest.raises(ValueError):
        get_plan(model="resnet18_4stage", mode="federated")


@pytest.mark.slow
def test_resnet18_4stage_pipeline_matches_fused(devices):
    """Config 4: 4-stage GPipe over a 4-device pipe mesh == monolithic."""
    cfg = Config(mode="split", batch_size=BATCH, microbatches=2)
    plan = get_plan(model="resnet18_4stage", mode="split")
    data = [cifar_batch(i) for i in range(2)]

    mesh = make_mesh(num_clients=1, num_stages=4, devices=devices[:4])
    pipe = PipelinedTrainer(plan, cfg, jax.random.PRNGKey(1), data[0][0], mesh)
    pipe_losses = [pipe.train_step(x, y) for x, y in data]

    ref = FusedSplitTrainer(plan, Config(mode="split", batch_size=BATCH),
                            jax.random.PRNGKey(1), data[0][0])
    ref_losses = [ref.train_step(x, y) for x, y in data]
    np.testing.assert_allclose(pipe_losses, ref_losses, rtol=1e-4, atol=1e-4)
