"""Test harness: 8 virtual CPU devices replace multi-chip hardware.

The reference uses k3d (Docker-in-Docker k8s) as its fake cluster
(SURVEY.md §4); here the fake backend is XLA's host-platform device count —
mesh/ppermute/psum tests run against 8 virtual CPU devices. Must be set
before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force, ambient env says "axon"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import pytest  # noqa: E402

# Hermeticity: the image's sitecustomize registers an "axon" TPU backend
# that proxies to a local tunnel; its lazy init runs even under
# JAX_PLATFORMS=cpu and hangs when the tunnel is wedged. Tests never want
# the real chip — drop the factory before any backend initializes.
jax.config.update("jax_platforms", "cpu")
try:
    import jax._src.xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass


# Honor SLT_FLIGHT for the suite the way the CLI does (obs/flight.py):
# a CI job exporting SLT_FLIGHT=<path> gets a causal event journal from
# the tests' own runtimes, dumped on any watchdog trip. Unset (the
# default) this returns None and the recorder stays off — the pinned
# bit-identity tests in tests/test_flight.py rely on that.
from split_learning_tpu.obs import flight as _obs_flight  # noqa: E402

_obs_flight.maybe_enable_from_env()


@pytest.fixture(scope="session", autouse=True)
def _lock_watchdog_gate():
    """Under SLT_LOCK_DEBUG=1 the runtime locks report inversions and
    hold-budget violations into obs/locks.py's default graph; any such
    report from the suite's own runtimes is a real bug — fail the
    session at teardown. (The intentional-inversion regression test
    uses a private LockGraph, so it never trips this gate.)"""
    from split_learning_tpu.obs import locks
    yield
    if locks.enabled():
        violations = locks.default_graph().violations
        assert not violations, (
            "lock watchdog reports from the test session:\n" +
            "\n".join(v["message"] for v in violations))


@pytest.fixture(scope="session", autouse=True)
def _dispatch_watchdog_gate():
    """Under SLT_DISPATCH_DEBUG=1 the runtimes run their jitted calls
    inside dispatch_debug step scopes; a steady-state recompile (local
    ordinal >= 2 with a previously-seen signature) or an unexpected-D2H
    report from the suite's own trainers is a real bug — fail the
    session at teardown. (Watchdog regression tests use private
    DispatchTracker instances, so they never trip this gate; arming is
    env-only — dispatch_debug.force() bench overrides don't count.)"""
    from split_learning_tpu.obs import dispatch_debug
    yield
    if os.environ.get("SLT_DISPATCH_DEBUG", "") not in ("", "0"):
        violations = dispatch_debug.tracker().violations
        assert not violations, (
            "dispatch watchdog reports from the test session:\n" +
            "\n".join(v["message"] for v in violations))


@pytest.fixture(scope="session")
def devices():
    # NOTE: ask for the cpu backend explicitly — bare jax.devices() resolves
    # the *default* backend, which the installed axon TPU shim hijacks to
    # open a (possibly hanging) tunnel connection even under JAX_PLATFORMS=cpu.
    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture()
def mnist_batch(rng):
    """A deterministic fake MNIST batch (reference batch size 64)."""
    kx, ky = jax.random.split(rng)
    x = jax.random.normal(kx, (64, 28, 28, 1), jnp.float32)
    y = jax.random.randint(ky, (64,), 0, 10)
    return x, y
