"""Test harness: 8 virtual CPU devices replace multi-chip hardware.

The reference uses k3d (Docker-in-Docker k8s) as its fake cluster
(SURVEY.md §4); here the fake backend is XLA's host-platform device count —
mesh/ppermute/psum tests run against 8 virtual CPU devices. Must be set
before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture()
def mnist_batch(rng):
    """A deterministic fake MNIST batch (reference batch size 64)."""
    kx, ky = jax.random.split(rng)
    x = jax.random.normal(kx, (64, 28, 28, 1), jnp.float32)
    y = jax.random.randint(ky, (64,), 0, 10)
    return x, y
