"""Horizontal server replication (runtime/replica.py): sticky
rendezvous routing, the exactly-once failover handoff (quiesce ->
capture -> merge -> commit), FedAvg group sync, and the
zero-overhead-off pin — the acceptance criteria of the replication
issue. Heavy legs use real ServerRuntime replicas (the coalesce-test
recipe); protocol legs use a jax-light stub around a real ReplayCache,
the same surface slt-check's replica_death_handoff scenario drives."""

import glob
import os
import threading

import jax
import numpy as np
import pytest

from split_learning_tpu.models import get_plan
from split_learning_tpu.runtime import (
    ReplicaGroup, ServerRuntime, maybe_replicate, rendezvous_pick)
from split_learning_tpu.runtime.replay import ReplayCache
from split_learning_tpu.utils import Config

BATCH = 8


def server_factory(n_clients=64, **kw):
    cfg = Config(mode="split", batch_size=BATCH, num_clients=n_clients)
    plan = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)

    def factory(_idx: int) -> ServerRuntime:
        # every replica shares the init (same plan/cfg/key): the group
        # is statistically one model
        return ServerRuntime(plan, cfg, jax.random.PRNGKey(0), sample,
                             strict_steps=True, **kw)
    return factory


def batch(seed, n=BATCH):
    # the server side of the split consumes CUT-shape activations
    # (the fleet-harness wire contract), not raw images
    rs = np.random.RandomState(seed)
    y = rs.randint(0, 10, (n,))
    x = rs.randn(n, 26, 26, 32).astype(np.float32)
    return x, y.astype(np.int64)


class _StubReplica:
    """The claim lifecycle of ServerRuntime.split_step minus jax: a
    real ReplayCache decides ownership, only the owner applies, and
    the reply records which payload materialized it — so a duplicate
    carrying a garbage payload can only come back identical to the
    original if it was served from replay, never re-applied."""

    def __init__(self, idx):
        self.idx = idx
        self.replay = ReplayCache(window=16)
        self.applies = []

    def health(self):
        return {"step": len(self.applies), "status": "serving"}

    def split_step(self, payload, labels, step, client_id=0):
        entry, owner = self.replay.begin(client_id, "split_step", step)
        if not owner:
            return self.replay.wait(entry, timeout=30.0)
        self.applies.append((client_id, step, payload))
        value = ("reply", client_id, step, self.idx, payload)
        self.replay.resolve(entry, value)
        return value

    def flush_deferred(self):
        return 0

    def export_runtime_extras(self, step):
        from split_learning_tpu.runtime.checkpoint import build_extras
        return build_extras(step, 1, replay=self.replay.export_state(),
                            wire_ef=[])

    def close(self):
        pass


# --------------------------------------------------------------------- #
# routing
# --------------------------------------------------------------------- #

def test_rendezvous_routing_sticky_and_minimal_churn():
    """Same client -> same replica on every call (sticky), every
    replica gets traffic, and removing one replica moves ONLY its
    clients (HRW's minimal-churn property — the reason reroutes after
    a kill are bounded by the victim's share)."""
    ids = [0, 1, 2]
    first = {c: rendezvous_pick(c, ids) for c in range(256)}
    again = {c: rendezvous_pick(c, ids) for c in range(256)}
    assert first == again
    assert set(first.values()) == {0, 1, 2}
    survivors = [0, 2]
    for c in range(256):
        after = rendezvous_pick(c, survivors)
        if first[c] != 1:
            assert after == first[c], f"client {c} moved without cause"
        else:
            assert after in survivors
    with pytest.raises(ValueError):
        rendezvous_pick(0, [])


def test_group_assignment_matches_pure_function():
    group = ReplicaGroup([_StubReplica(i) for i in range(3)])
    for c in range(64):
        assert group.assignment(c) == rendezvous_pick(c, [0, 1, 2])


# --------------------------------------------------------------------- #
# exactly-once across the handoff (stub protocol legs)
# --------------------------------------------------------------------- #

def test_handoff_never_double_applies_garbage_dup():
    """Kill the client's replica after its step applied, then
    retransmit the step with a DIFFERENT (garbage) payload: the
    successor must answer from the migrated replay entry — the
    original reply, original payload — and apply nothing."""
    group = ReplicaGroup([_StubReplica(i) for i in range(2)])
    victim = group.assignment(0)
    orig = group.split_step("orig-payload", None, 1, 0)
    group.kill(victim)

    dup = group.split_step("garbage-payload", None, 1, 0)
    assert dup == orig
    assert dup[-1] == "orig-payload"
    total_applies = [a for r in group.replicas for a in r.applies
                     if a[0] == 0 and a[1] == 1]
    assert len(total_applies) == 1
    counters = group.counters()
    assert counters["replica_handoffs"] == 1
    assert counters["handoff_replay_entries"] >= 1
    assert group.live_replicas() == [1 - victim]
    # the bystander's fresh traffic still lands (and applies once)
    other = next(c for c in range(1, 32)
                 if rendezvous_pick(c, [0, 1]) != victim)
    group.split_step("fresh", None, 1, other)
    assert len(group.replicas[1 - victim].applies) >= 1


def test_kill_mid_flight_duplicate_blocks_then_serves():
    """A duplicate racing the kill: it enters the router while the
    handoff fence is up, blocks on handoff_done instead of rerouting
    early, and is then served the migrated original reply."""
    group = ReplicaGroup([_StubReplica(i) for i in range(2)])
    victim = group.assignment(0)
    orig = group.split_step("orig", None, 3, 0)

    results = {}

    def dup():
        results["dup"] = group.split_step("retransmit", None, 3, 0)

    killer = threading.Thread(target=group.kill, args=(victim,))
    killer.start()
    t = threading.Thread(target=dup)
    t.start()
    killer.join(timeout=30)
    t.join(timeout=30)
    assert not t.is_alive() and not killer.is_alive()
    assert results["dup"] == orig
    assert group.counters()["replica_handoffs"] == 1


def test_checkpoint_handoff_roundtrip_lock_debug(tmp_path, monkeypatch):
    """handoff='checkpoint': the captured extras go through the
    durable sidecar path (tmp+fsync+rename under ckpt_dir) and the
    successor restores from what disk holds. Run with SLT_LOCK_DEBUG=1
    so the instrumented locks police the fence/quiesce ordering."""
    monkeypatch.setenv("SLT_LOCK_DEBUG", "1")
    group = ReplicaGroup([_StubReplica(i) for i in range(2)],
                         handoff="checkpoint", ckpt_dir=str(tmp_path))
    victim = group.assignment(0)
    orig = group.split_step("orig", None, 1, 0)
    group.kill(victim)
    # the durable artifact exists on disk
    assert glob.glob(os.path.join(str(tmp_path), "**", "*"),
                     recursive=True)
    # and the successor serves the dup from what it restored
    assert group.split_step("garbage", None, 1, 0) == orig
    assert group.counters()["handoff_replay_entries"] >= 1


# --------------------------------------------------------------------- #
# real-server legs: bit-identity and FedAvg sync
# --------------------------------------------------------------------- #

def test_maybe_replicate_one_is_zero_overhead():
    """--replicas 1 must change NOTHING: the factory's bare runtime
    comes back (no router object, no extra indirection)."""
    sentinel = object()
    calls = []

    def factory(idx):
        calls.append(idx)
        return sentinel

    out = maybe_replicate(factory, 1)
    assert out is sentinel
    assert calls == [0]
    assert not isinstance(out, ReplicaGroup)
    assert isinstance(maybe_replicate(lambda i: _StubReplica(i), 2),
                      ReplicaGroup)


def test_replicas_one_bit_identical_to_plain_server():
    factory = server_factory()
    plain = factory(0)
    solo = maybe_replicate(factory, 1, sync_every=1)
    try:
        for step in range(1, 4):
            x, y = batch(step)
            _, loss_p = plain.split_step(x, y, step, 0)
            _, loss_s = solo.split_step(x, y, step, 0)
            assert loss_p == loss_s, (step, loss_p, loss_s)
    finally:
        plain.close()
        solo.close()


def test_fedavg_sync_equalizes_replica_params():
    """After sync_now the live replicas hold the SAME params (one
    FedAvg mean, copied per replica so the donated-buffer step never
    aliases across replicas) — and training continues afterwards."""
    group = maybe_replicate(server_factory(), 2)
    try:
        # drive two clients that land on different replicas so the
        # replicas' params genuinely diverge first
        a = next(c for c in range(32) if group.assignment(c) == 0)
        b = next(c for c in range(32) if group.assignment(c) == 1)
        for step in range(1, 3):
            xa, ya = batch(step)
            xb, yb = batch(100 + step)
            group.split_step(xa, ya, step, a)
            group.split_step(xb, yb, step, b)
        p0 = group.replicas[0].export_state().params
        p1 = group.replicas[1].export_state().params
        diverged = any(
            not np.array_equal(np.asarray(l0), np.asarray(l1))
            for l0, l1 in zip(jax.tree_util.tree_leaves(p0),
                              jax.tree_util.tree_leaves(p1)))
        assert diverged, "replicas should diverge before sync"

        group.sync_now()
        p0 = group.replicas[0].export_state().params
        p1 = group.replicas[1].export_state().params
        for l0, l1 in zip(jax.tree_util.tree_leaves(p0),
                          jax.tree_util.tree_leaves(p1)):
            np.testing.assert_array_equal(np.asarray(l0),
                                          np.asarray(l1))
        assert group.counters()["replica_syncs"] == 1

        # post-sync steps still run (the copies really are per-replica
        # buffers; a shared donated buffer would crash here)
        x, y = batch(9)
        group.split_step(x, y, 3, a)
        group.split_step(x, y, 3, b)
    finally:
        group.close()


# --------------------------------------------------------------------- #
# compressed-wire handoff (PR 18): the storage-free EF contract
# --------------------------------------------------------------------- #

def test_clapping_handoff_migrates_no_ef_ledger():
    """Clapping-mode replicas (storage-free EF, arXiv:2509.19029) hand
    off NO residual ledger: the victim's extras capture omits wire_ef
    entirely and is measurably smaller than a topk8 twin's holding the
    identical in-memory residuals, the handoff merges zero EF entries
    where the topk8 group merges at least one — and in both modes the
    rerouted duplicate is still served the original reply, bit for
    bit."""
    from split_learning_tpu.transport import codec as wire_codec

    sizes = {}
    for mode in ("topk8", "clapping"):
        group = ReplicaGroup(
            [server_factory(ef_mode=mode)(i) for i in range(2)])
        try:
            victim = group.assignment(0)
            # the victim packs one compressed reply for client 0,
            # leaving a real residual in its ledger; the successor's
            # ledger has no entry for that stream (merge_state keeps
            # local keys, so a shared key would merge as zero)
            rs = np.random.RandomState(1)
            g = rs.randn(4096).astype(np.float32)
            group.replicas[victim].wire_ef.compress(
                (0, "/forward_pass"), g, 0.1)
            x, y = batch(7)
            orig_g, orig_loss = group.split_step(x, y, 0, 0)

            cap = group.replicas[victim].export_runtime_extras(0)
            sizes[mode] = len(wire_codec.encode(cap))
            if mode == "clapping":
                assert "wire_ef" not in cap
            else:
                assert "wire_ef" in cap

            group.kill(victim)
            ctr = group.counters()
            if mode == "clapping":
                assert ctr["handoff_ef_entries"] == 0
            else:
                assert ctr["handoff_ef_entries"] >= 1
            # the dup after the kill: replayed original, never re-applied
            dup_g, dup_loss = group.split_step(x, y, 0, 0)
            np.testing.assert_array_equal(np.asarray(dup_g),
                                          np.asarray(orig_g))
            assert dup_loss == orig_loss
        finally:
            group.close()
    assert sizes["clapping"] < sizes["topk8"], sizes
