"""scripts/tpu_window_runner.py main-loop semantics, simulated.

The runner is round-critical infrastructure (every on-chip number this
round flows through it), so its state machine is pinned: completed legs
are never re-run, a timeout/error breaks back to probing without
burning an attempt on every remaining leg, attempts cap per leg class
(MAX_ATTEMPTS for exploratory, MUST_LAND_ATTEMPTS for the round's
priority set — tests/test_runner_schedule.py), and the deadline frees
the tunnel for the round-end driver bench."""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def runner(tmp_path, monkeypatch):
    path = os.path.join(REPO, "scripts", "tpu_window_runner.py")
    spec = importlib.util.spec_from_file_location("twr_sim", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, REPO)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "STATE", str(tmp_path / "state.json"))
    monkeypatch.setattr(mod, "OUT", str(tmp_path / "runs.jsonl"))
    monkeypatch.setattr(mod.time, "sleep", lambda s: None)
    # the real assemblers touch artifacts/ — out of scope here (probe,
    # which embeds the canary, is stubbed per test)
    monkeypatch.setattr(mod, "run_assemblers", lambda: None)
    return mod


def read_out(mod):
    with open(mod.OUT) as f:
        return [json.loads(line) for line in f]


def test_done_legs_never_rerun_and_wedge_breaks(runner, monkeypatch):
    legs = [{"id": "a", "role": "fused", "env": {}, "quick": True,
             "timeout": 1},
            {"id": "b", "role": "fused", "env": {}, "quick": True,
             "timeout": 1},
            {"id": "c", "role": "fused", "env": {}, "quick": True,
             "timeout": 1}]
    monkeypatch.setattr(runner, "LEGS", legs)
    monkeypatch.setattr(runner, "probe", lambda: True)

    calls = []
    # window 1: a ok, b times out (wedge) -> break; window 2: b ok, c ok
    script = {("a", 1): "ok", ("b", 1): "timeout", ("b", 2): "ok",
              ("c", 1): "ok"}

    def fake_run_leg(leg):
        n = sum(1 for c in calls if c == leg["id"]) + 1
        calls.append(leg["id"])
        return {"leg": leg["id"], "status": script[(leg["id"], n)]}

    monkeypatch.setattr(runner, "run_leg", fake_run_leg)
    runner.main()
    # a ran once only; b's timeout broke the window before c started
    assert calls == ["a", "b", "b", "c"]
    st = runner.load_state()
    assert sorted(st["done"]) == ["a", "b", "c"]
    assert read_out(runner)[-1]["leg"] == "__runner_done__"


def test_attempts_cap_exhausts_a_dead_leg(runner, monkeypatch):
    monkeypatch.setattr(runner, "LEGS", [
        {"id": "dead", "role": "fused", "env": {}, "quick": True,
         "timeout": 1}])
    monkeypatch.setattr(runner, "probe", lambda: True)
    calls = []

    def fake_run_leg(leg):
        calls.append(leg["id"])
        return {"leg": leg["id"], "status": "error"}

    monkeypatch.setattr(runner, "run_leg", fake_run_leg)
    runner.main()
    assert len(calls) == runner.MAX_ATTEMPTS
    assert runner.load_state()["done"] == []


def test_deadline_exits_before_next_leg(runner, monkeypatch):
    monkeypatch.setattr(runner, "LEGS", [
        {"id": "x", "role": "fused", "env": {}, "quick": True,
         "timeout": 1}])
    monkeypatch.setattr(runner, "probe", lambda: True)
    monkeypatch.setattr(runner, "DEADLINE", 0.0)  # already past
    monkeypatch.setattr(runner, "run_leg",
                        lambda leg: pytest.fail("leg ran past deadline"))
    runner.main()
    assert read_out(runner)[-1]["leg"] == "__runner_deadline__"


def test_invalid_and_oom_mark_done(runner, monkeypatch):
    legs = [{"id": "i", "role": "fused", "env": {}, "quick": True,
             "timeout": 1},
            {"id": "o", "role": "fused", "env": {}, "quick": True,
             "timeout": 1}]
    monkeypatch.setattr(runner, "LEGS", legs)
    monkeypatch.setattr(runner, "probe", lambda: True)
    monkeypatch.setattr(runner, "run_leg", lambda leg: {
        "leg": leg["id"],
        "status": "invalid" if leg["id"] == "i" else "oom"})
    runner.main()
    assert sorted(runner.load_state()["done"]) == ["i", "o"]


def test_canary_record_lands_per_window(runner, monkeypatch):
    """Each live window opens with the probe's chip-sanity canary
    record, the context needed to attribute anomalous legs (healthy
    canary = the leg; sick canary = pooled-chip contention) — and a
    canary that errors still leaves a record, since the sickest
    windows are the ones that most need attributing."""
    monkeypatch.setattr(runner, "LEGS", [
        {"id": "a", "role": "fused", "env": {}, "quick": True,
         "timeout": 9}])
    monkeypatch.setattr(runner, "probe", lambda: {"tflops": 123.0})
    monkeypatch.setattr(runner, "run_leg",
                        lambda leg: {"leg": leg["id"], "status": "ok",
                                     "result": {"valid": True}})
    runner.main()
    recs = read_out(runner)
    kinds = [r["leg"] for r in recs]
    assert "__canary__" in kinds
    assert kinds.index("__canary__") < kinds.index("a")
    canary = next(r for r in recs if r["leg"] == "__canary__")
    assert canary["status"] == "ok"
    assert canary["result"]["tflops"] == 123.0


def test_canary_error_skips_window_and_deadline_assembles(
        runner, monkeypatch):
    """ADVICE r4: a window that answers the probe but fails the matmul
    canary gets NO legs (it would burn bounded MAX_ATTEMPTS on a sick
    chip) but still leaves its error record; the next healthy window
    proceeds normally."""
    monkeypatch.setattr(runner, "LEGS", [
        {"id": "a", "role": "fused", "env": {}, "quick": True,
         "timeout": 9}])
    probes = iter([{"canary_error": "no CANARY line"},
                   {"tflops": 99.0}])
    monkeypatch.setattr(runner, "probe", lambda: next(probes))
    ran = []

    def fake_run_leg(leg):
        ran.append(leg["id"])
        return {"leg": leg["id"], "status": "ok",
                "result": {"valid": True}}

    monkeypatch.setattr(runner, "run_leg", fake_run_leg)
    runner.main()
    recs = read_out(runner)
    kinds = [r["leg"] for r in recs]
    # sick window: error canary recorded, leg NOT run in it; healthy
    # window: ok canary, then the leg
    assert ran == ["a"]
    assert kinds.index("a") > kinds.index("__canary__")
    statuses = [r["status"] for r in recs if r["leg"] == "__canary__"]
    assert statuses == ["error", "ok"]

    # deadline exit also assembles (the likely exit on a flaky tunnel)
    assembled = []
    monkeypatch.setattr(runner, "run_assemblers",
                        lambda: assembled.append(True))
    monkeypatch.setattr(runner, "DEADLINE", 0.0)
    monkeypatch.setattr(runner, "STATE", runner.STATE + ".2")
    monkeypatch.setattr(runner, "LEGS", [
        {"id": "b", "role": "fused", "env": {}, "quick": True,
         "timeout": 9}])
    runner.main()
    assert assembled == [True]
