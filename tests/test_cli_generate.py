"""The `generate` CLI subcommand (launch/run.py cmd_generate): decode
from a causal-LM checkpoint — greedy/sampled, KV-cache/re-forward —
with the library decode stack (runtime/generate.py) underneath."""

import json
import os

import pytest

from split_learning_tpu.launch.run import main


def test_generate_rejects_non_lm_checkpoint(tmp_path, capsys):
    ck = tmp_path / "ck"
    os.makedirs(ck)
    with open(ck / "meta.json", "w") as f:
        json.dump({"layout": "fused", "mode": "split",
                   "model": "split_cnn", "dataset": "synthetic"}, f)
    rc = main(["generate", "--checkpoint-dir", str(ck),
               "--data-dir", str(tmp_path)])
    assert rc == 2
    assert "transformer_lm" in capsys.readouterr().err


def test_generate_rejects_bad_prompt(tmp_path, capsys):
    ck = tmp_path / "ck"
    os.makedirs(ck)
    with open(ck / "meta.json", "w") as f:
        json.dump({"layout": "fused", "mode": "split",
                   "model": "transformer_lm", "dataset": "lm"}, f)
    rc = main(["generate", "--checkpoint-dir", str(ck),
               "--prompt", "1,two,3", "--data-dir", str(tmp_path)])
    assert rc == 2
    assert "token ids" in capsys.readouterr().err


@pytest.mark.slow
def test_generate_roundtrip_greedy_and_sampled(tmp_path, capsys):
    """Train a tiny LM checkpoint, then decode: greedy is deterministic
    and identical between the KV-cache and re-forward paths; sampling
    honors the explicit prompt."""
    ck = str(tmp_path / "ck")
    rc = main(["train", "--transport", "fused", "--dataset", "lm",
               "--model", "transformer_lm", "--batch-size", "8",
               "--steps", "6", "--tracking", "noop",
               "--checkpoint-dir", ck, "--data-dir", str(tmp_path)])
    assert rc == 0
    capsys.readouterr()

    def gen(*extra):
        rc = main(["generate", "--checkpoint-dir", ck, "--n-new", "6",
                   "--data-dir", str(tmp_path), *extra])
        assert rc == 0
        return json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    greedy = gen()
    assert greedy["decode"] == "greedy"
    assert len(greedy["tokens"][0]) == 6
    again = gen()
    assert again["tokens"] == greedy["tokens"]
    reforward = gen("--no-kv-cache")
    assert reforward["tokens"] == greedy["tokens"]

    sampled = gen("--prompt", "3,1,4,1,5", "--temperature", "0.9",
                  "--top-k", "12")
    assert sampled["decode"] == "sampled"
    assert sampled["prompt"] == [[3, 1, 4, 1, 5]]
    assert len(sampled["tokens"][0]) == 6


def test_generate_rejects_bad_sampling_flags(tmp_path, capsys):
    ck = tmp_path / "ck"
    os.makedirs(ck)
    with open(ck / "meta.json", "w") as f:
        json.dump({"layout": "fused", "mode": "split",
                   "model": "transformer_lm", "dataset": "lm"}, f)
    base = ["generate", "--checkpoint-dir", str(ck),
            "--data-dir", str(tmp_path)]
    assert main(base + ["--temperature", "0"]) == 2
    assert "greedy" in capsys.readouterr().err
    assert main(base + ["--top-p", "0"]) == 2
    assert "top-p" in capsys.readouterr().err
    assert main(base + ["--top-k", "-1"]) == 2
    assert "top-k" in capsys.readouterr().err
    assert main(base + ["--prompt=-3,5"]) == 2
    assert ">= 0" in capsys.readouterr().err


@pytest.mark.slow
@pytest.mark.parametrize("transport,port", [
    ("local", 18411),   # split_local layout: per-party subtrees
    ("fused", 18517),   # joint whole-plan tree: serve slices its stage
])
def test_cli_split_party_decode_roundtrip(tmp_path, capsys, transport,
                                          port):
    """The full CLI story for BOTH checkpoint layouts: train a sized LM,
    stand the server party up with `serve --resume`, decode split-party
    with `generate --server-url` — token-exact vs the local composed
    decode (both halves share the checkpoint weights)."""
    import threading
    import time
    import urllib.request

    ck = str(tmp_path / "ck")
    rc = main(["train", "--model", "transformer_lm", "--dataset", "lm",
               "--transport", transport, "--d-model", "32", "--num-heads",
               "2", "--seq-len", "16", "--steps", "4", "--batch-size", "8",
               "--tracking", "noop", "--checkpoint-dir", ck,
               "--data-dir", str(tmp_path)])
    assert rc == 0
    capsys.readouterr()

    threading.Thread(
        target=main,
        args=(["serve", "--model", "transformer_lm", "--dataset", "lm",
               "--port", str(port), "--tracking", "noop",
               "--checkpoint-dir", ck, "--resume",
               "--data-dir", str(tmp_path)],), daemon=True).start()
    for _ in range(60):
        time.sleep(0.5)
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=2)
            break
        except Exception:
            continue
    else:
        raise AssertionError("serve never became healthy")
    capsys.readouterr()

    def gen(*extra):
        rc = main(["generate", "--checkpoint-dir", ck, "--prompt",
                   "1,2,3", "--n-new", "4", "--data-dir", str(tmp_path),
                   *extra])
        assert rc == 0
        return json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    local = gen()
    remote = gen("--server-url", f"http://127.0.0.1:{port}")
    assert remote["remote_server"].endswith(str(port))
    assert remote["tokens"] == local["tokens"]


@pytest.mark.slow
def test_serve_resume_rejects_serverless_layout(tmp_path, capsys):
    """A checkpoint written by a client whose server was remote carries
    no server half: serve --resume must exit 2 with a clear error, not
    an uncaught KeyError."""
    import numpy as np

    from split_learning_tpu.runtime.checkpoint import Checkpointer

    ck = tmp_path / "ck"
    os.makedirs(ck)
    with open(ck / "meta.json", "w") as f:
        json.dump({"layout": "client_only", "mode": "split",
                   "model": "split_cnn", "dataset": "synthetic"}, f)
    ckptr = Checkpointer(str(ck))
    ckptr.save(3, {"client": {"params": {"w": np.zeros(2)}}})
    ckptr.close()

    rc = main(["serve", "--checkpoint-dir", str(ck), "--resume",
               "--tracking", "noop", "--data-dir", str(tmp_path)])
    assert rc == 2
    assert "no server subtree" in capsys.readouterr().err
