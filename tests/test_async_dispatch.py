"""Async server dispatch (PR 5): the lock covers only admission + the
jitted call, host materialization runs off-lock (``d2h``), and the
client can stage batches on device while a step is in flight
(``DevicePrefetch``). The synthetic ``d2h_delay_s`` knob widens the
materialization window so lock behavior is observable on CPU JAX, which
has no real transfer cost."""

import threading
import time

import jax
import numpy as np
import pytest

from split_learning_tpu import obs
from split_learning_tpu.data.datasets import DevicePrefetch
from split_learning_tpu.obs import locks
from split_learning_tpu.models import get_plan
from split_learning_tpu.obs.metrics import (Histogram, histogram_percentile,
                                            render_prometheus)
from split_learning_tpu.runtime import ServerRuntime, SplitClientTrainer
from split_learning_tpu.runtime.multi_client import MultiClientSplitRunner
from split_learning_tpu.transport.http import HttpTransport
from split_learning_tpu.transport.local import LocalTransport
from split_learning_tpu.utils import Config

BATCH = 4


def _server(**kw):
    cfg = Config(mode="split", batch_size=BATCH, num_clients=2)
    plan = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    return cfg, plan, ServerRuntime(plan, cfg, jax.random.PRNGKey(2),
                                    sample, **kw)


def _batch(seed=0):
    rs = np.random.RandomState(seed)
    return (rs.randn(BATCH, 28, 28, 1).astype(np.float32),
            rs.randint(0, 10, BATCH).astype(np.int64))


# ---------------------------------------------------------------------- #
# the tentpole: materialization runs off the lock
# ---------------------------------------------------------------------- #

def _health_latency_during_step(overlap: bool) -> float:
    """Start a step whose materialization is padded to 0.4 s, then time
    health() — which needs the runtime lock — while it runs."""
    cfg, plan, server = _server(overlap=overlap, d2h_delay_s=0.4)
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server))
    x, y = _batch()
    client.train_step(x, y, 0)  # compile + first padded materialization

    t = threading.Thread(target=client.train_step, args=(x, y, 1))
    t.start()
    # by now the step thread is inside the server: dispatch is a few ms
    # after warmup, so it is sitting in the 0.4 s materialization window
    time.sleep(0.1)
    t0 = time.perf_counter()
    server.health()
    dt = time.perf_counter() - t0
    t.join()
    server.close()
    return dt


def test_materialization_does_not_hold_the_lock():
    """With overlap on, health() gets the lock while the step's D2H is
    still in flight; with overlap off the same call blocks behind the
    materialization — the direct observable of the async-dispatch
    restructure."""
    assert _health_latency_during_step(overlap=True) < 0.15
    assert _health_latency_during_step(overlap=False) > 0.15


def test_overlap_loss_series_bit_identical():
    """Moving the D2H off the lock cannot change numerics: same jitted
    program, same application order — the sequential loss series must
    match bit for bit."""
    def series(overlap):
        cfg, plan, server = _server(overlap=overlap)
        client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                    LocalTransport(server))
        try:
            return [client.train_step(*_batch(i), i) for i in range(4)]
        finally:
            server.close()

    assert series(True) == series(False)


def test_concurrent_smoke_records_d2h_off_lock():
    """N=2 concurrent clients, traced: every step records a ``d2h`` span
    at least as long as the synthetic delay while the ``dispatch`` span
    (the lock-held window) stays well under it — i.e. the transfer
    really left the lock — and the ``lock_hold`` histogram populates and
    renders as slt_lock_hold_seconds. This is the CI overlap smoke."""
    d2h = 0.08
    cfg, plan, server = _server(overlap=True, d2h_delay_s=d2h)
    runner = MultiClientSplitRunner(
        plan, cfg, jax.random.PRNGKey(1),
        lambda i: LocalTransport(server),
        num_clients=2, concurrent=True)
    rs = np.random.RandomState(0)
    x = rs.randn(3, 2, BATCH, 28, 28, 1).astype(np.float32)
    y = rs.randint(0, 10, (3, 2, BATCH)).astype(np.int64)
    try:
        runner.train_round(list(zip(x[0], y[0])))  # untraced warmup
        tr = obs.enable()
        try:
            for r in range(1, 3):
                runner.train_round(list(zip(x[r], y[r])))
        finally:
            obs.disable()
        snap = server.metrics()
    finally:
        runner.close()
        server.close()

    spans = tr.spans()
    d2h_spans = [s for s in spans if s["name"] == "d2h"]
    assert len(d2h_spans) == 4  # 2 rounds x 2 clients
    assert all(s["party"] == "server" for s in d2h_spans)
    assert all(s["duration"] >= d2h for s in d2h_spans)

    hists = snap["histograms"]
    assert hists["d2h"]["count"] == 4
    text = render_prometheus(snap)
    assert "slt_d2h_seconds_count 4" in text
    # under SLT_LOCK_DEBUG=1 the obs/locks.py watchdog also feeds
    # lock_hold (one observation per outermost acquisition, warmup
    # included), so the exact traced-step tally only holds watchdog-off
    if locks.enabled():
        assert hists["lock_hold"]["count"] >= 4
    else:
        assert hists["lock_hold"]["count"] == 4
        assert "slt_lock_hold_seconds_count 4" in text
        # lock-held window excludes the materialization: its p50 sits
        # far below the padded transfer the old taxonomy would have
        # absorbed
        assert histogram_percentile(hists["lock_hold"], 50) < d2h / 2
    assert histogram_percentile(hists["dispatch"], 50) < d2h / 2


def test_histogram_percentile():
    h = Histogram(buckets=(0.01, 0.1, 1.0))
    assert histogram_percentile(h.snapshot(), 50) == 0.0  # empty
    for v in [0.005] * 50 + [0.5] * 50:
        h.observe(v)
    snap = h.snapshot()
    assert histogram_percentile(snap, 25) <= 0.01
    assert 0.1 < histogram_percentile(snap, 75) <= 1.0
    assert histogram_percentile(snap, 100) == 1.0
    h.observe(5.0)  # +Inf slot clamps to last finite bound
    assert histogram_percentile(h.snapshot(), 100) == 1.0
    with pytest.raises(ValueError):
        histogram_percentile(snap, 101)


# ---------------------------------------------------------------------- #
# satellite: HTTP connection pool must not serialize wide windows
# ---------------------------------------------------------------------- #

def test_http_transport_pool_sizing():
    """urllib3's default pool of 10 silently serializes >10 concurrent
    lanes on a shared session; the transport must mount an adapter sized
    to its pool_maxsize (default 32 >= any sane --pipeline-depth)."""
    t = HttpTransport("http://127.0.0.1:1")
    try:
        adapter = t._session.get_adapter("http://127.0.0.1:1/step")
        assert adapter._pool_maxsize == 32
        assert adapter._pool_connections == 32
    finally:
        t.close()

    t = HttpTransport("http://127.0.0.1:1", pool_maxsize=48)
    try:
        assert t.pool_maxsize == 48
        assert t._session.get_adapter("http://x")._pool_maxsize == 48
        assert t._session.get_adapter("https://x")._pool_maxsize == 48
    finally:
        t.close()

    with pytest.raises(ValueError, match="pool_maxsize"):
        HttpTransport("http://127.0.0.1:1", pool_maxsize=0)


# ---------------------------------------------------------------------- #
# satellite: DevicePrefetch
# ---------------------------------------------------------------------- #

def test_device_prefetch_yields_identical_sequence():
    batches = [(np.full((2, 3), i, np.float32), np.arange(3) + i)
               for i in range(7)]
    with DevicePrefetch(batches, depth=3) as pf:
        out = list(pf)
    assert len(out) == len(batches)
    for (x, y), (xd, yd) in zip(batches, out):
        assert isinstance(xd, jax.Array)  # staged on device
        np.testing.assert_array_equal(np.asarray(xd), x)
        np.testing.assert_array_equal(yd, y)  # labels pass through


def test_device_prefetch_drains_cleanly_on_early_exit():
    def gen():
        for i in range(10_000):
            yield np.full((2, 2), i, np.float32), i

    pf = DevicePrefetch(gen(), depth=2)
    first = next(pf)
    assert float(np.asarray(first[0])[0, 0]) == 0.0
    pf.close()
    assert not pf._thread.is_alive()  # no leaked staging thread
    with pytest.raises(StopIteration):
        next(pf)
    pf.close()  # idempotent


def test_device_prefetch_propagates_source_error():
    def bad():
        yield np.zeros((1, 1), np.float32), 0
        raise RuntimeError("boom")

    pf = DevicePrefetch(bad(), depth=1)
    next(pf)
    with pytest.raises(RuntimeError, match="boom"):
        next(pf)
    assert not pf._thread.is_alive()

    with pytest.raises(ValueError, match="depth"):
        DevicePrefetch([], depth=0)


def test_trainer_prefetch_loss_parity():
    """train(prefetch=N) must reproduce the unprefetched run bit for bit
    — device staging is value-preserving and order is FIFO."""
    batches = [(_batch(i)) for i in range(5)]

    def run(prefetch):
        cfg, plan, server = _server()
        client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                    LocalTransport(server))
        try:
            recs = client.train(lambda: iter(batches), epochs=1,
                                prefetch=prefetch)
            return [r.loss for r in recs]
        finally:
            server.close()

    assert run(0) == run(2)


def test_multi_client_train_rounds_with_prefetch():
    cfg, plan, server = _server()
    runner = MultiClientSplitRunner(
        plan, cfg, jax.random.PRNGKey(1),
        lambda i: LocalTransport(server), num_clients=2)
    iters = [[_batch(10 * c + r) for r in range(3)] for c in range(2)]
    try:
        losses = runner.train_rounds(iters, prefetch=1)
    finally:
        runner.close()
        server.close()
    # drains when the iterators do: 3 rounds of 2 clients, finite losses
    assert len(losses) == 3 and all(len(r) == 2 for r in losses)
    assert all(np.isfinite(l) for r in losses for l in r)
