"""Admission control + continuous batching: token-bucket quotas, EDF
deadlines, typed backpressure end-to-end (LocalTransport and HTTP 429),
the breaker's no-failure quota wait, and the continuous batcher's loss
parity with the serialized path."""

import threading
import time

import jax
import numpy as np
import pytest

from split_learning_tpu.models import get_plan
from split_learning_tpu.runtime import (
    CircuitBreaker, ContinuousBatcher, ServerRuntime, SplitClientTrainer)
from split_learning_tpu.runtime.admission import AdmissionController
from split_learning_tpu.runtime.client import FailurePolicy
from split_learning_tpu.runtime.coalesce import RequestCoalescer
from split_learning_tpu.transport.base import Backpressure
from split_learning_tpu.transport.http import HttpTransport, SplitHTTPServer
from split_learning_tpu.transport.local import LocalTransport
from split_learning_tpu.utils import Config

BATCH = 8


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_server(coalesce_max=1, window_ms=50.0, batching="window",
                tenants=1, quota=None, slo_ms=None, n_clients=64):
    cfg = Config(mode="split", batch_size=BATCH, num_clients=n_clients)
    plan = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), sample,
                           strict_steps=True, coalesce_max=coalesce_max,
                           coalesce_window_ms=window_ms, batching=batching,
                           tenants=tenants, quota=quota, slo_ms=slo_ms)
    return cfg, plan, server


# --------------------------------------------------------------------- #
# unit: the token bucket, no jax involved
# --------------------------------------------------------------------- #

def test_token_bucket_quota_and_retry_after():
    clock = FakeClock()
    ac = AdmissionController(tenants=1, quota=2.0, burst=2, clock=clock)
    ac.admit(0)
    ac.admit(0)
    with pytest.raises(Backpressure) as exc_info:
        ac.admit(0)
    # bucket empty at rate 2/s: one token is 0.5s away
    assert exc_info.value.retry_after_s == pytest.approx(0.5)
    clock.advance(0.5)
    ac.admit(0)  # refilled
    c = ac.counters()
    assert c["admission_admitted"] == 3
    assert c["admission_rejected"] == 1


def test_quota_is_per_tenant_and_burst_caps_refill():
    clock = FakeClock()
    ac = AdmissionController(tenants=2, quota=[1.0, 100.0], burst=[1, 100],
                             clock=clock)
    ac.admit(0)          # tenant 0 = client 0
    with pytest.raises(Backpressure):
        ac.admit(2)      # still tenant 0 (client_id % tenants)
    for cid in (1, 3, 5):
        ac.admit(cid)    # tenant 1 has its own, bigger bucket
    # a long idle period must not bank more than `burst` tokens
    clock.advance(3600.0)
    ac.admit(0)
    with pytest.raises(Backpressure):
        ac.admit(0)


def test_quota_starvation_fairness():
    """One tenant offering 10x its quota must not starve the other:
    each tenant's admitted share tracks its own bucket, so the
    saturating tenant is clipped to ~quota while the polite tenant
    gets everything it asked for."""
    clock = FakeClock()
    quota = 5.0
    ac = AdmissionController(tenants=2, quota=quota, burst=1, clock=clock)
    admitted = {0: 0, 1: 0}
    offered = {0: 0, 1: 0}
    tick = 0.01
    for i in range(1000):             # 10 simulated seconds
        clock.advance(tick)
        offered[0] += 1               # tenant 0: 100/s, 20x quota
        try:
            ac.admit(0)
            admitted[0] += 1
        except Backpressure:
            pass
        if i % 25 == 0:               # tenant 1: 4/s, under quota
            offered[1] += 1
            try:
                ac.admit(1)
                admitted[1] += 1
            except Backpressure:
                pass
    # saturating tenant clipped to its quota (50 tokens in 10s +- burst)
    assert admitted[0] == pytest.approx(quota * 10.0, rel=0.1)
    # polite tenant admitted everything
    assert admitted[1] == offered[1]
    gauges = ac.gauges()
    assert set(gauges) == {"admission_queue_depth_t0",
                           "admission_queue_depth_t1"}


def test_admission_deadline_from_slo():
    clock = FakeClock()
    clock.t = 100.0
    ac = AdmissionController(tenants=2, slo_ms=[50.0, 500.0], clock=clock)
    assert ac.admit(0) == pytest.approx(100.05)
    assert ac.admit(1) == pytest.approx(100.5)
    ac_none = AdmissionController(tenants=1, clock=clock)
    assert ac_none.admit(0) is None


def test_admission_rejects_bad_config():
    with pytest.raises(ValueError):
        AdmissionController(tenants=0)
    with pytest.raises(ValueError):
        AdmissionController(tenants=2, quota=[1.0, -1.0])
    with pytest.raises(ValueError):
        AdmissionController(tenants=2, quota=[1.0, 2.0, 3.0])


# --------------------------------------------------------------------- #
# breaker: an advised wait is not a failure
# --------------------------------------------------------------------- #

def test_breaker_backpressure_wait_virtual_clock():
    slept = []
    br = CircuitBreaker(lambda: None, failure_threshold=2,
                        sleep=slept.append)
    br.backpressure_wait(1.5)
    assert slept == [1.5]
    assert br.state == "closed"
    assert br.counters["breaker_backpressure_waits"] == 1
    # the advised wait did not count toward the failure threshold
    br.record_failure()
    assert br.state == "closed"
    br.backpressure_wait(0.25)
    br.record_failure()          # second REAL failure trips it
    assert br.state == "open"
    assert slept == [1.5, 0.25]


# --------------------------------------------------------------------- #
# coalescer: continuous mode + graceful shutdown
# --------------------------------------------------------------------- #

def _resolve_all(group, reason):
    for r in group:
        r.result = (r.acts, float(len(group)))
        r.done.set()


def test_continuous_lone_submit_ignores_window():
    """The continuous flusher never sleeps on the window timer while
    work is queued: a lone request dispatches immediately even with an
    absurd window."""
    groups = []

    def dispatch(group, reason):
        groups.append((len(group), reason))
        _resolve_all(group, reason)

    cb = ContinuousBatcher(dispatch, max_group=4, window_s=3600.0)
    try:
        t0 = time.perf_counter()
        acts = np.zeros((2, 3), np.float32)
        labels = np.zeros((2,), np.int64)
        cb.submit(acts, labels, 0, 0)
        assert time.perf_counter() - t0 < 5.0
        assert groups == [(1, "continuous")]
    finally:
        cb.close()


def test_continuous_edf_order_and_adaptive_group():
    """While a dispatch is in flight, queued requests pile up; the next
    group is picked deadline-first (EDF) and sized to whatever is
    admitted, up to max_group."""
    release = threading.Event()
    groups = []

    def dispatch(group, reason):
        groups.append([r.client_id for r in group])
        release.wait(5.0)
        _resolve_all(group, reason)

    cb = ContinuousBatcher(dispatch, max_group=4)
    try:
        acts = np.zeros((1, 2), np.float32)
        labels = np.zeros((1,), np.int64)

        def submit(cid, deadline):
            return threading.Thread(
                target=cb.submit, args=(acts, labels, 0, cid),
                kwargs={"deadline": deadline}, daemon=True)

        threads = [submit(0, None)]
        threads[0].start()
        time.sleep(0.2)  # first request is now in-flight, holding the flusher
        # queued while busy: EDF must order them 3 (t=1.0) then 2 (t=9.0)
        # then 1 (no deadline -> last)
        for cid, dl in ((1, None), (2, 9.0), (3, 1.0)):
            threads.append(submit(cid, dl))
            threads[-1].start()
            time.sleep(0.05)
        release.set()
        for th in threads:
            th.join(timeout=5.0)
        assert groups[0] == [0]
        assert groups[1] == [3, 2, 1]
    finally:
        cb.close()


def test_continuous_edf_equal_deadlines_pick_up_in_arrival_order():
    """Equal deadlines must tie-break on arrival (submit) order, not on
    whatever order the scheduler woke the submitters in — pins the
    ``(deadline, seq)`` sort key so the group composition is
    deterministic (slt-check's edf_pickup_order relies on it)."""
    release = threading.Event()
    groups = []

    def dispatch(group, reason):
        groups.append([r.client_id for r in group])
        release.wait(5.0)
        _resolve_all(group, reason)

    cb = ContinuousBatcher(dispatch, max_group=4)
    try:
        acts = np.zeros((1, 2), np.float32)
        labels = np.zeros((1,), np.int64)
        first = threading.Thread(
            target=cb.submit, args=(acts, labels, 0, 0),
            kwargs={"deadline": None}, daemon=True)
        first.start()
        time.sleep(0.2)  # in flight, holding the flusher
        threads = [first]
        # all the same deadline: pickup must preserve 7, 5, 6 arrival order
        for cid in (7, 5, 6):
            th = threading.Thread(
                target=cb.submit, args=(acts, labels, 0, cid),
                kwargs={"deadline": 4.0}, daemon=True)
            threads.append(th)
            th.start()
            th_seen = time.time() + 2.0
            while time.time() < th_seen:   # wait until queued, keeps order
                with cb._cond:
                    queued = len(cb._queue)
                if queued >= len(threads) - 1:
                    break
                time.sleep(0.005)
        release.set()
        for th in threads:
            th.join(timeout=5.0)
        assert groups[0] == [0]
        assert groups[1] == [7, 5, 6]
    finally:
        cb.close()


def test_coalescer_close_fails_queued_requests():
    """close() on a wedged flusher must fail still-queued requests with
    a terminal error, not leave their waiters hanging out the full
    submit() timeout."""
    entered = threading.Event()
    release = threading.Event()

    def dispatch(group, reason):
        entered.set()
        release.wait(30.0)  # wedged until the test releases it
        _resolve_all(group, reason)

    rc = RequestCoalescer(dispatch, max_group=2, window_s=0.05)
    acts = np.zeros((1, 2), np.float32)
    labels = np.zeros((1,), np.int64)
    t0 = threading.Thread(target=rc.submit, args=(acts, labels, 0, 0),
                          daemon=True)
    t0.start()
    assert entered.wait(5.0)  # first group is in-flight, wedged
    # this one is queued behind the wedged dispatch when close() lands
    err = {}

    def second():
        try:
            rc.submit(acts, labels, 0, 1)
        except RuntimeError as exc:
            err["exc"] = exc

    t1 = threading.Thread(target=second, daemon=True)
    t1.start()
    time.sleep(0.2)
    t_close = time.perf_counter()
    rc.close(timeout=0.5)  # join times out on the wedged dispatch
    assert time.perf_counter() - t_close < 5.0
    t1.join(timeout=5.0)
    assert not t1.is_alive()
    assert "closed before dispatch" in str(err["exc"])
    release.set()  # unwedge so the first waiter resolves normally
    t0.join(timeout=5.0)
    assert not t0.is_alive()


# --------------------------------------------------------------------- #
# integration: continuous batching on a real server
# --------------------------------------------------------------------- #

def batch(seed, n=BATCH):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 28, 28, 1).astype(np.float32)
    y = rs.randint(0, 10, (n,)).astype(np.int64)
    return x, y


def test_continuous_single_client_matches_serialized():
    """Capacity-1 continuous batching (every group is one request) must
    reproduce the serialized path's training trajectory."""
    losses = {}
    for mode, coalesce_max in (("serialized", 1), ("continuous", 4)):
        cfg, plan, server = make_server(
            coalesce_max=coalesce_max, window_ms=50.0,
            batching="continuous" if coalesce_max > 1 else "window")
        try:
            client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                        LocalTransport(server))
            run = []
            for step in range(6):
                x, y = batch(step)
                run.append(client.train_step(x, y, step))
            losses[mode] = run
        finally:
            server.close()
    np.testing.assert_allclose(losses["continuous"], losses["serialized"],
                               atol=1e-4)


def test_local_transport_surfaces_backpressure():
    """An over-quota step raises typed Backpressure through the local
    wire, with an actionable retry_after, and releases the replay claim
    so the retried step is not treated as a duplicate."""
    cfg, plan, server = make_server(tenants=1, quota=0.001)
    try:
        transport = LocalTransport(server)
        rs = np.random.RandomState(0)
        acts = rs.randn(BATCH, 26, 26, 32).astype(np.float32)
        labels = rs.randint(0, 10, (BATCH,)).astype(np.int64)
        transport.split_step(acts, labels, 0)          # burst token
        with pytest.raises(Backpressure) as exc_info:
            transport.split_step(acts, labels, 1)
        assert exc_info.value.retry_after_s > 0
        adm = server.health()["admission"]
        assert adm["admission_rejected"] == 1
        # claim released: the same step succeeds once the bucket refills
        # (fed directly to the controller via its public clock, no sleep)
        server._admission._tokens[0] = 1.0
        transport.split_step(acts, labels, 1)
    finally:
        server.close()


def test_client_skip_policy_drops_on_backpressure():
    cfg, plan, server = make_server(tenants=1, quota=0.001)
    try:
        client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                    LocalTransport(server),
                                    failure_policy=FailurePolicy.SKIP)
        x, y = batch(0)
        assert client.train_step(x, y, 0) is not None   # burst token
        assert client.train_step(batch(1)[0], batch(1)[1], 1) is None
        assert client.dropped_batches == 1
    finally:
        server.close()


def test_http_429_retry_after_round_trip():
    """HTTP twin of the local-wire contract: the handler maps
    Backpressure to 429 + Retry-After, the client maps it back."""
    cfg, plan, server = make_server(tenants=1, quota=0.001)
    http = SplitHTTPServer(server).start()
    transport = HttpTransport(http.url)
    try:
        rs = np.random.RandomState(0)
        acts = rs.randn(BATCH, 26, 26, 32).astype(np.float32)
        labels = rs.randint(0, 10, (BATCH,)).astype(np.int64)
        transport.split_step(acts, labels, 0)
        with pytest.raises(Backpressure) as exc_info:
            transport.split_step(acts, labels, 1)
        assert exc_info.value.retry_after_s > 0
    finally:
        transport.close()
        http.stop()
        server.close()


def test_server_health_reports_admission_and_batching():
    cfg, plan, server = make_server(coalesce_max=4, batching="continuous",
                                    tenants=2, quota=50.0, slo_ms=250.0)
    try:
        h = server.health()
        assert h["coalescing"]["batching"] == "continuous"
        adm = h["admission"]
        assert adm["tenants"] == 2
        assert adm["quota"] == [50.0, 50.0]
        assert adm["slo_ms"] == [250.0, 250.0]
        m = server.metrics()
        assert "admission_admitted" in m["counters"]
        assert "admission_queue_depth_t0" in m["gauges"]
    finally:
        server.close()


def test_server_rejects_continuous_without_coalescing():
    with pytest.raises(ValueError):
        make_server(coalesce_max=1, batching="continuous")
    with pytest.raises(ValueError):
        make_server(batching="sometimes")
