"""Native C++ codec: builds with the baked-in toolchain and matches the
NumPy reference implementation bit-for-bit (same absmax scale, same
round-half-even quantization, zlib-identical CRC-32)."""

import zlib

import numpy as np
import pytest

from split_learning_tpu import native
from split_learning_tpu.transport import codec


def _numpy_q8(a: np.ndarray):
    scale = max(float(np.max(np.abs(a))) / 127.0, 1e-12) if a.size else 1e-12
    q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
    return q, scale


@pytest.fixture(scope="module")
def built():
    if not native.available():
        pytest.skip(f"native codec unavailable: {native.build_error()}")
    return True


def test_builds(built):
    assert native.available()


def test_quantize_matches_numpy(built):
    rs = np.random.RandomState(0)
    for shape in [(64, 32, 26, 26), (1,), (17, 3), (0,)]:
        a = (rs.randn(*shape) * 5).astype(np.float32)
        nat = native.q8_quantize(a)
        assert nat is not None
        q_nat, s_nat = nat
        q_np, s_np = _numpy_q8(a)
        assert s_nat == pytest.approx(s_np, rel=0, abs=0)
        np.testing.assert_array_equal(q_nat, q_np)


def test_dequantize_matches_numpy(built):
    rs = np.random.RandomState(1)
    q = rs.randint(-127, 128, (1000,)).astype(np.int8)
    scale = 0.037
    out = native.q8_dequantize(q, scale)
    np.testing.assert_array_equal(out, q.astype(np.float32) * np.float32(scale))


def test_crc32_matches_zlib(built):
    for data in [b"", b"hello", bytes(range(256)) * 100]:
        assert native.crc32(data) == zlib.crc32(data) & 0xFFFFFFFF


def test_q8_roundtrip_through_wire_codec(built):
    """q8_compress (native path) -> encode -> decode -> decompress."""
    rs = np.random.RandomState(2)
    a = rs.randn(64, 32, 26, 26).astype(np.float32)
    blob = codec.encode({"acts": codec.q8_compress(a)})
    out = codec.decompress_tree(codec.decode(blob))["acts"]
    assert out.shape == a.shape and out.dtype == a.dtype
    # quantization error bounded by the step size
    step = float(np.max(np.abs(a))) / 127.0
    assert float(np.max(np.abs(out - a))) <= step * 0.5 + 1e-6


def test_checksum_fallback_identical():
    """codec.checksum is CRC-32 whether or not the native lib built."""
    data = b"x" * 10000
    assert codec.checksum(data) == zlib.crc32(data) & 0xFFFFFFFF


def test_multithreaded_consistency(built):
    rs = np.random.RandomState(3)
    a = rs.randn(2_000_000).astype(np.float32)
    q1, s1 = native.q8_quantize(a, n_threads=1)
    q8, s8 = native.q8_quantize(a, n_threads=8)
    assert s1 == s8
    np.testing.assert_array_equal(q1, q8)
