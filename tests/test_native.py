"""Native C++ codec: builds with the baked-in toolchain and matches the
NumPy reference implementation bit-for-bit (same absmax scale, same
round-half-even quantization, zlib-identical CRC-32)."""

import zlib

import numpy as np
import pytest

from split_learning_tpu import native
from split_learning_tpu.transport import codec


def _numpy_q8(a: np.ndarray):
    scale = max(float(np.max(np.abs(a))) / 127.0, 1e-12) if a.size else 1e-12
    q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
    return q, scale


@pytest.fixture(scope="module")
def built():
    if not native.available():
        pytest.skip(f"native codec unavailable: {native.build_error()}")
    return True


def test_builds(built):
    assert native.available()


def test_quantize_matches_numpy(built):
    rs = np.random.RandomState(0)
    for shape in [(64, 32, 26, 26), (1,), (17, 3), (0,)]:
        a = (rs.randn(*shape) * 5).astype(np.float32)
        nat = native.q8_quantize(a)
        assert nat is not None
        q_nat, s_nat = nat
        q_np, s_np = _numpy_q8(a)
        assert s_nat == pytest.approx(s_np, rel=0, abs=0)
        np.testing.assert_array_equal(q_nat, q_np)


def test_dequantize_matches_numpy(built):
    rs = np.random.RandomState(1)
    q = rs.randint(-127, 128, (1000,)).astype(np.int8)
    scale = 0.037
    out = native.q8_dequantize(q, scale)
    np.testing.assert_array_equal(out, q.astype(np.float32) * np.float32(scale))


def test_crc32_matches_zlib(built):
    for data in [b"", b"hello", bytes(range(256)) * 100]:
        assert native.crc32(data) == zlib.crc32(data) & 0xFFFFFFFF


def test_q8_roundtrip_through_wire_codec(built):
    """q8_compress (native path) -> encode -> decode -> decompress."""
    rs = np.random.RandomState(2)
    a = rs.randn(64, 32, 26, 26).astype(np.float32)
    blob = codec.encode({"acts": codec.q8_compress(a)})
    out = codec.decompress_tree(codec.decode(blob))["acts"]
    assert out.shape == a.shape and out.dtype == a.dtype
    # quantization error bounded by the step size
    step = float(np.max(np.abs(a))) / 127.0
    assert float(np.max(np.abs(out - a))) <= step * 0.5 + 1e-6


def test_checksum_fallback_identical():
    """codec.checksum is CRC-32 whether or not the native lib built."""
    data = b"x" * 10000
    assert codec.checksum(data) == zlib.crc32(data) & 0xFFFFFFFF


def test_multithreaded_consistency(built):
    rs = np.random.RandomState(3)
    a = rs.randn(2_000_000).astype(np.float32)
    q1, s1 = native.q8_quantize(a, n_threads=1)
    q8, s8 = native.q8_quantize(a, n_threads=8)
    assert s1 == s8
    np.testing.assert_array_equal(q1, q8)


# --------------------------------------------------------------------- #
# non-finite guard: a single NaN/Inf poisons the absmax scale and the
# whole tensor decodes as NaN silently — both quantize entry points must
# refuse loudly, on the native path and the NumPy fallback alike
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("bad", [np.nan, np.inf, -np.inf])
def test_q8_rejects_non_finite(bad):
    a = np.ones((4, 7), np.float32)
    a[2, 3] = bad
    with pytest.raises(codec.CodecError) as ei:
        codec.q8_compress(a)
    assert "[4, 7]" in str(ei.value) and "float32" in str(ei.value)


@pytest.mark.parametrize("bad", [np.nan, np.inf])
def test_topk8_rejects_non_finite(bad):
    a = np.ones((3, 5), np.float32)
    a[0, 0] = bad
    with pytest.raises(codec.CodecError):
        codec.topk8_compress(a, 0.5)


def test_non_finite_guard_covers_numpy_fallback(monkeypatch):
    """Force the fallback (native.q8_quantize -> None) and check the
    guard fires before it, identically to the native path."""
    monkeypatch.setattr(native, "q8_quantize", lambda *a, **kw: None)
    monkeypatch.setattr(native, "topk8_select", lambda *a, **kw: None)
    a = np.full((2, 2), np.nan, np.float32)
    with pytest.raises(codec.CodecError):
        codec.q8_compress(a)
    with pytest.raises(codec.CodecError):
        codec.topk8_compress(a, 0.5)
    # and the fallback still works on clean input
    good = np.arange(8, dtype=np.float32).reshape(2, 4)
    out = codec.q8_decompress(codec.q8_compress(good))
    assert out.shape == good.shape


# --------------------------------------------------------------------- #
# topk8 select/scatter: the C++ kernels must reproduce the NumPy
# reference rule exactly (all |v| > thr, then lowest-index ties to k,
# ascending) — the two ends of a wire may run different paths
# --------------------------------------------------------------------- #
def test_topk8_select_matches_numpy(built):
    rs = np.random.RandomState(4)
    for n, k in [(100, 10), (2_163_200, 216320), (513 * 128 + 7, 1000),
                 (50, 50), (17, 1)]:
        a = (rs.randn(n) * 3).astype(np.float32)
        nat = native.topk8_select(a, k)
        assert nat is not None
        idx_n, vals_n = nat
        idx_p, vals_p = codec._topk8_select_numpy(a, k)
        np.testing.assert_array_equal(idx_n, idx_p)
        np.testing.assert_array_equal(vals_n, vals_p)


def test_topk8_select_tie_rule(built):
    """Heavy ties: many elements share the threshold magnitude; both
    paths must keep the lowest-index ones."""
    rs = np.random.RandomState(5)
    a = rs.choice([-2.0, -1.0, 1.0, 2.0], size=10_000).astype(np.float32)
    for k in (1, 7, 500, 9_999):
        nat = native.topk8_select(a, k)
        assert nat is not None
        idx_n, vals_n = nat
        idx_p, vals_p = codec._topk8_select_numpy(a, k)
        np.testing.assert_array_equal(idx_n, idx_p)
        np.testing.assert_array_equal(vals_n, vals_p)


def test_topk8_select_thread_counts_agree(built):
    rs = np.random.RandomState(6)
    a = rs.randn(1_000_000).astype(np.float32)
    i1, v1 = native.topk8_select(a, 100_000, n_threads=1)
    i8, v8 = native.topk8_select(a, 100_000, n_threads=8)
    np.testing.assert_array_equal(i1, i8)
    np.testing.assert_array_equal(v1, v8)


def test_topk8_scatter_matches_numpy(built):
    rs = np.random.RandomState(7)
    n, k = 500_000, 50_000
    idx = np.sort(rs.choice(n, size=k, replace=False)).astype(np.int64)
    q = rs.randint(-127, 128, k).astype(np.int8)
    scale = 0.0123
    nat = native.topk8_scatter(idx, q, scale, n)
    assert nat is not None
    ref = np.zeros(n, np.float32)
    ref[idx] = q.astype(np.float32) * np.float32(scale)
    np.testing.assert_array_equal(nat, ref)


def test_topk8_wire_roundtrip_native_vs_fallback(built, monkeypatch):
    """Full compress->encode->decode->decompress parity: native on, then
    forced off — identical wire trees and identical reconstructions."""
    rs = np.random.RandomState(8)
    a = (rs.randn(64, 26, 26, 32) * 2).astype(np.float32)
    packed_nat, res_nat = codec.topk8_compress(a, 0.1)
    out_nat = codec.decompress_tree(codec.decode(codec.encode(packed_nat)))
    monkeypatch.setattr(native, "topk8_select", lambda *x, **kw: None)
    monkeypatch.setattr(native, "topk8_scatter", lambda *x, **kw: None)
    monkeypatch.setattr(native, "q8_quantize", lambda *x, **kw: None)
    packed_py, res_py = codec.topk8_compress(a, 0.1)
    out_py = codec.decompress_tree(codec.decode(codec.encode(packed_py)))
    assert packed_nat["scale"] == pytest.approx(packed_py["scale"],
                                                rel=1e-6)
    np.testing.assert_array_equal(packed_nat["q"], packed_py["q"])
    np.testing.assert_array_equal(out_nat, out_py)
    np.testing.assert_allclose(res_nat, res_py, rtol=0, atol=0)
