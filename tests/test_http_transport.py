"""HTTP transport over a real loopback socket: wire parity with the local
transport, error-status mapping, and payload accounting."""

import jax
import numpy as np
import pytest

from split_learning_tpu.models import get_plan
from split_learning_tpu.runtime import (
    ProtocolError, ServerRuntime, SplitClientTrainer)
from split_learning_tpu.transport import LocalTransport, TransportError
from split_learning_tpu.transport.http import HttpTransport, SplitHTTPServer
from split_learning_tpu.utils import Config
from split_learning_tpu.version import __version__

BATCH = 8


@pytest.fixture()
def http_pair():
    cfg = Config(mode="split", batch_size=BATCH)
    plan = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    runtime = ServerRuntime(plan, cfg, jax.random.PRNGKey(2), sample)
    server = SplitHTTPServer(runtime).start()
    transport = HttpTransport(server.url)
    yield cfg, plan, runtime, server, transport
    transport.close()
    server.stop()


def test_http_split_step_and_training(http_pair):
    cfg, plan, runtime, server, transport = http_pair
    h = transport.health()
    uptime = h.pop("uptime_seconds")
    assert uptime >= 0.0
    assert h == {"status": "healthy", "mode": "split",
                 "model_type": "part_b", "step": -1,
                 "strict_steps": True, "version": __version__}

    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(2), transport)
    rs = np.random.RandomState(1)
    losses = []
    for step in range(5):
        x = rs.randn(BATCH, 28, 28, 1).astype(np.float32)
        y = rs.randint(0, 10, (BATCH,)).astype(np.int64)
        losses.append(client.train_step(x, y, step))
    assert all(np.isfinite(l) for l in losses)
    s = transport.stats.summary()
    assert s["round_trips"] == 5
    # cut-layer payload: [8,26,26,32] fp32 ≈ 0.66 MiB each way + labels
    assert s["bytes_sent"] > 8 * 26 * 26 * 32 * 4 * 5
    assert s["bytes_received"] > 8 * 26 * 26 * 32 * 4 * 5


def test_http_matches_local_transport(http_pair):
    """Same server math regardless of wire: HTTP == in-process."""
    cfg, plan, runtime, server, transport = http_pair
    cfg2 = Config(mode="split", batch_size=BATCH)
    plan2 = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    runtime2 = ServerRuntime(plan2, cfg2, jax.random.PRNGKey(2), sample)
    local = LocalTransport(runtime2, through_codec=True)

    rs = np.random.RandomState(3)
    acts = rs.randn(BATCH, 26, 26, 32).astype(np.float32)
    labels = rs.randint(0, 10, (BATCH,)).astype(np.int64)
    g_http, l_http = transport.split_step(acts, labels, 0)
    g_local, l_local = local.split_step(acts, labels, 0)
    np.testing.assert_allclose(g_http, g_local, rtol=1e-6, atol=1e-7)
    assert abs(l_http - l_local) < 1e-6


def test_http_error_status_mapping(http_pair):
    cfg, plan, runtime, server, transport = http_pair
    acts = np.zeros((2, 26, 26, 32), np.float32)
    labels = np.zeros((2,), np.int64)
    g0, l0 = transport.split_step(acts, labels, step=10)
    # duplicate of an applied step -> the cached original reply,
    # bit-identical (exactly-once within the replay window)
    g1, l1 = transport.split_step(acts, labels, step=10)
    np.testing.assert_array_equal(g0, g1)
    assert l0 == l1
    assert runtime.replay.body_hits >= 1  # served raw original bytes
    # below the window the 409 remains: evict step 10, then replay it
    for s in range(11, 11 + runtime.replay.window + 1):
        transport.split_step(acts, labels, step=s)
    with pytest.raises(ProtocolError):
        transport.split_step(acts, labels, step=10)
    # 400 mode guard -> ProtocolError
    with pytest.raises(ProtocolError):
        transport.aggregate({"w": np.zeros(2, np.float32)}, 0, 0.0, 99)
    # connection refused -> TransportError (transient)
    dead = HttpTransport("http://127.0.0.1:9")
    with pytest.raises(TransportError):
        dead.health()


def test_wait_ready_barrier_blocks_until_server_up():
    """The readiness barrier the reference lacks (SURVEY.md §3.4): a client
    started before its server must wait at /health, not drop batches."""
    import socket
    import threading
    import time

    # reserve a port, start the server only after a delay
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    cfg = Config(mode="split", batch_size=BATCH)
    plan = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    runtime = ServerRuntime(plan, cfg, jax.random.PRNGKey(2), sample)
    started = {}

    def late_start():
        time.sleep(0.8)
        started["server"] = SplitHTTPServer(runtime, port=port).start()

    t = threading.Thread(target=late_start)
    t.start()
    transport = HttpTransport(f"http://127.0.0.1:{port}")
    try:
        t0 = time.monotonic()
        info = transport.wait_ready(timeout=10.0, interval=0.1)
        waited = time.monotonic() - t0
        assert info["status"] == "healthy" and info["mode"] == "split"
        assert waited >= 0.5, "barrier returned before the server was up"
    finally:
        t.join()
        transport.close()
        started["server"].stop()


def test_wait_ready_times_out_cleanly():
    dead = HttpTransport("http://127.0.0.1:9")
    with pytest.raises(TransportError):
        dead.wait_ready(timeout=0.5, interval=0.1)
    dead.close()


def test_wait_ready_polls_on_exponential_backoff(monkeypatch):
    """Satellite: the readiness poll doubles from ``interval`` up to
    ``max_interval`` (then clamps to the deadline) instead of the old
    fixed 0.5 s — N restarting-server waiters back off instead of
    thundering-herding. Virtual clock: sleep lengths ARE the schedule."""
    import time

    slept = []
    clock = {"t": 0.0}
    monkeypatch.setattr(time, "monotonic", lambda: clock["t"])

    def fake_sleep(s):
        slept.append(s)
        clock["t"] += s

    monkeypatch.setattr(time, "sleep", fake_sleep)
    dead = HttpTransport("http://127.0.0.1:9")
    with pytest.raises(TransportError):
        dead.wait_ready(timeout=10.0, interval=0.1, max_interval=5.0,
                        jitter=0.0)
    dead.close()
    # 0.1 doubling to the 5.0 cap, final wait clamped to the deadline
    assert slept == pytest.approx([0.1, 0.2, 0.4, 0.8, 1.6, 3.2, 3.7])
