"""Data pipeline (cache semantics, parsers, batcher) and tracking backends."""

import gzip
import json
import os
import re
import struct

import numpy as np
import pytest

from split_learning_tpu.data import (
    LocalStore, Split, batches, epoch_steps, load_dataset, synthetic)
from split_learning_tpu.data.datasets import MNIST_MEAN, MNIST_STD
from split_learning_tpu.tracking import (
    JsonlLogger, MultiLogger, StdoutLogger, experiment_name, make_logger)
from split_learning_tpu.utils import Config


def _write_idx_mnist(d, n_train=64, n_test=16):
    rs = np.random.RandomState(0)

    def images(n):
        return struct.pack(">IIII", 0x803, n, 28, 28) + \
            rs.randint(0, 256, (n, 28, 28), dtype=np.uint8).tobytes()

    def labels(n):
        return struct.pack(">II", 0x801, n) + \
            rs.randint(0, 10, (n,), dtype=np.uint8).tobytes()

    os.makedirs(d, exist_ok=True)
    for name, blob in [("train-images-idx3-ubyte", images(n_train)),
                       ("train-labels-idx1-ubyte", labels(n_train)),
                       ("t10k-images-idx3-ubyte.gz", gzip.compress(images(n_test))),
                       ("t10k-labels-idx1-ubyte.gz", gzip.compress(labels(n_test)))]:
        with open(os.path.join(d, name), "wb") as f:
            f.write(blob)


def test_mnist_idx_load_and_cache_roundtrip(tmp_path):
    d = str(tmp_path)
    _write_idx_mnist(d)
    ds = load_dataset("mnist", d)
    assert not ds.synthetic
    assert ds.train.x.shape == (64, 28, 28, 1)
    assert ds.train.x.dtype == np.float32
    # normalization parity with the reference (src/client_part.py:61-64)
    raw_zero = (0.0 - MNIST_MEAN) / MNIST_STD
    assert abs(ds.train.x.min() - raw_zero) < 0.3

    # second load hits the cache blob (delete raws to prove it)
    for f in os.listdir(d):
        if "ubyte" in f:
            os.remove(os.path.join(d, f))
    ds2 = load_dataset("mnist", d)
    np.testing.assert_array_equal(ds.train.x, ds2.train.x)
    np.testing.assert_array_equal(ds.train.y, ds2.train.y)


def test_cifar10_binary_load(tmp_path):
    d = str(tmp_path)
    rs = np.random.RandomState(1)
    rec = lambda n: np.concatenate(
        [rs.randint(0, 10, (n, 1), dtype=np.uint8),
         rs.randint(0, 256, (n, 3072), dtype=np.uint8)], axis=1).tobytes()
    for i in range(1, 6):
        with open(os.path.join(d, f"data_batch_{i}.bin"), "wb") as f:
            f.write(rec(20))
    with open(os.path.join(d, "test_batch.bin"), "wb") as f:
        f.write(rec(10))
    ds = load_dataset("cifar10", d)
    assert ds.train.x.shape == (100, 32, 32, 3)
    assert ds.test.x.shape == (10, 32, 32, 3)
    assert ds.num_classes == 10


def test_synthetic_cache_never_shadows_real_data(tmp_path):
    """Regression: a synthetic blob cached in a data-less environment must
    not satisfy allow_synthetic=False, and real files appearing later win."""
    d = str(tmp_path)
    ds = load_dataset("mnist", d)  # no raws yet -> synthetic, cached
    assert ds.synthetic
    with pytest.raises(FileNotFoundError):
        load_dataset("mnist", d, allow_synthetic=False)
    _write_idx_mnist(d)  # real files appear
    ds2 = load_dataset("mnist", d, allow_synthetic=False)
    assert not ds2.synthetic
    # and the real blob is now the cached one
    ds3 = load_dataset("mnist", d)
    assert not ds3.synthetic


def test_synthetic_fallback_and_determinism(tmp_path):
    ds1 = load_dataset("mnist", str(tmp_path / "a"))
    ds2 = load_dataset("mnist", str(tmp_path / "b"))
    assert ds1.synthetic and ds2.synthetic
    np.testing.assert_array_equal(ds1.train.x, ds2.train.x)

    with pytest.raises(FileNotFoundError):
        load_dataset("mnist", str(tmp_path / "c"), allow_synthetic=False)
    with pytest.raises(ValueError):
        load_dataset("imagenet", str(tmp_path))


def test_batcher_matches_reference_loader_shape():
    """938 steps/epoch on MNIST-60k at batch 64 (SURVEY.md §2 derived facts)."""
    assert epoch_steps(60_000, 64) == 938
    assert epoch_steps(60_000, 64, drop_remainder=True) == 937

    split = Split(np.zeros((130, 4, 4, 1), np.float32),
                  np.arange(130, dtype=np.int64))
    bs = list(batches(split, 64, seed=0))
    assert [len(b[1]) for b in bs] == [64, 64, 2]
    # seeded order is reproducible and covers every example exactly once
    bs2 = list(batches(split, 64, seed=0))
    np.testing.assert_array_equal(
        np.concatenate([b[1] for b in bs]),
        np.concatenate([b[1] for b in bs2]))
    assert set(np.concatenate([b[1] for b in bs]).tolist()) == set(range(130))


def test_local_store_atomic_put(tmp_path):
    store = LocalStore(str(tmp_path))
    assert not store.exists("k/v.bin")
    store.put("k/v.bin", b"abc")
    assert store.exists("k/v.bin")
    assert store.fetch("k/v.bin") == b"abc"


def test_experiment_name_parity():
    # ≡ f"{mode.capitalize()}_Learning_Sim" (src/server_part.py:20-21)
    assert experiment_name("split") == "Split_Learning_Sim"
    assert experiment_name("federated") == "Federated_Learning_Sim"
    assert experiment_name("u_split") == "Split_Learning_Sim"


def test_jsonl_logger_and_factory(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with JsonlLogger(path, experiment="Split_Learning_Sim", run_name="r") as lg:
        lg.log_metric("loss", 1.5, step=0)
        lg.log_metric("loss", 0.5, step=1)
        lg.log_params({"lr": 0.01})
    records = [json.loads(l) for l in open(path)]
    assert records[0]["key"] == "loss" and records[0]["value"] == 1.5
    assert records[2]["params"] == {"lr": 0.01}

    cfg = Config(tracking="jsonl", data_dir=str(tmp_path))
    lg = make_logger(cfg)
    lg.log_metric("loss", 1.0, step=0)
    lg.close()
    assert os.path.exists(
        os.path.join(str(tmp_path), "metrics", "Split_Learning_Sim.jsonl"))

    # mlflow is absent in this image: factory degrades loudly to stdout
    lg = make_logger(Config(tracking="mlflow"))
    assert isinstance(lg, StdoutLogger)
    with pytest.raises(ValueError):
        make_logger(Config(tracking="carrier-pigeon"))


def test_jsonl_logger_flushes_each_record(tmp_path):
    """Every record is flushed as a whole line while the logger is still
    open — a live reader (trace report against a running job) never sees
    a partially-buffered record. log_params round-trips too."""
    path = str(tmp_path / "live.jsonl")
    lg = JsonlLogger(path, experiment="E", run_name="r")
    try:
        lg.log_params({"lr": 0.01, "mode": "split", "clients": 4})
        lg.log_metric("loss", 2.25, step=7)
        # read back WITHOUT closing the logger
        lines = open(path).read().splitlines()
        assert len(lines) == 2
        params = json.loads(lines[0])
        assert params["params"] == {"lr": 0.01, "mode": "split",
                                    "clients": 4}
        assert params["experiment"] == "E" and params["run"] == "r"
        rec = json.loads(lines[1])
        assert rec["key"] == "loss" and rec["value"] == 2.25
        assert rec["step"] == 7
    finally:
        lg.close()


def test_multi_logger(capsys):
    lg = MultiLogger([StdoutLogger(every=1)])
    lg.log_metric("loss", 2.0, step=0)
    assert "loss: 2.0000" in capsys.readouterr().out


def test_log_artifact_is_noop_without_artifact_store(tmp_path):
    """Every backend accepts log_artifact; only mlflow persists it, so the
    CLI can call it unconditionally after checkpoint saves."""
    p = tmp_path / "ckpt"
    p.mkdir()
    for lg in (StdoutLogger(), MultiLogger([StdoutLogger()]),
               JsonlLogger(str(tmp_path / "m.jsonl"))):
        lg.log_artifact(str(p))  # must not raise
        lg.close()


# --------------------------------------------------------------------- #
# opt-in downloader (round-1 VERDICT missing #1) — against a local HTTP
# fixture, so the test stays hermetic while exercising the real
# urllib + sha256 + atomic-write path end to end.

import hashlib
import http.server
import threading

from split_learning_tpu.data.datasets import (
    ChecksumError, download_dataset)


@pytest.fixture()
def idx_http_server(tmp_path):
    """Serve generated MNIST IDX .gz files over local HTTP; yields
    (base_url, {filename: sha256})."""
    src = tmp_path / "srv"
    _write_idx_mnist(str(src))
    # the downloader fetches the canonical .gz names; gzip the two plain
    # files the fixture writes uncompressed
    for plain in ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"):
        data = (src / plain).read_bytes()
        (src / (plain + ".gz")).write_bytes(gzip.compress(data))
    sums = {}
    for p in src.iterdir():
        if p.name.endswith(".gz"):
            sums[p.name] = hashlib.sha256(p.read_bytes()).hexdigest()

    handler = lambda *a, **kw: http.server.SimpleHTTPRequestHandler(
        *a, directory=str(src), **kw)
    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{httpd.server_address[1]}/", sums
    finally:
        httpd.shutdown()


def _specs(base, sums):
    return [(name, base + name, sums[name]) for name in sorted(sums)]


def test_download_verifies_and_loads(tmp_path, idx_http_server):
    base, sums = idx_http_server
    dest = str(tmp_path / "data")
    fetched = download_dataset("mnist", dest, urls=_specs(base, sums))
    assert len(fetched) == 4
    ds = load_dataset("mnist", dest, allow_synthetic=False)
    assert not ds.synthetic and ds.train.x.shape[1:] == (28, 28, 1)
    # second call: cache hit, nothing re-downloaded
    assert download_dataset("mnist", dest, urls=_specs(base, sums)) == []


def test_download_rejects_checksum_mismatch(tmp_path, idx_http_server):
    base, sums = idx_http_server
    dest = str(tmp_path / "data")
    bad = [(n, u, "0" * 64) for n, u, _ in _specs(base, sums)]
    with pytest.raises(ChecksumError, match="sha256 mismatch"):
        download_dataset("mnist", dest, urls=bad)
    assert not os.path.exists(os.path.join(
        dest, "train-images-idx3-ubyte.gz")), "torn/bad file left behind"


def test_load_dataset_download_flag(tmp_path, idx_http_server, monkeypatch):
    """--require-real --download works with no pre-placed files: the
    VERDICT's done-criterion, against the local fixture."""
    import split_learning_tpu.data.datasets as dsm
    base, sums = idx_http_server
    monkeypatch.setitem(dsm._DOWNLOADS, "mnist", _specs(base, sums))
    dest = str(tmp_path / "fresh")
    ds = load_dataset("mnist", dest, allow_synthetic=False, download=True)
    assert not ds.synthetic and len(ds.train) == 64


def test_load_dataset_hermetic_default_unchanged(tmp_path):
    """Without download=True a raw miss still refuses (--require-real) —
    the downloader must never fire implicitly."""
    with pytest.raises(FileNotFoundError):
        load_dataset("mnist", str(tmp_path / "empty"),
                     allow_synthetic=False)


def test_download_pins_are_well_formed():
    """Every built-in recipe MUST carry a pin (round-2 VERDICT weak #6 —
    the unpinned CIFAR-10 hole), and a malformed pin (wrong
    length/charset) would hard-fail every valid download; catch typos
    structurally. Digests are '<hex>' (sha256) or '<algo>:<hex>'."""
    import hashlib
    from split_learning_tpu.data.datasets import _DOWNLOADS
    for name, specs in _DOWNLOADS.items():
        for fname, url, digest in specs:
            assert url.startswith("https://"), (name, fname)
            assert digest is not None, (
                f"{name}/{fname}: built-in recipes must be pinned")
            algo, _, hexval = digest.rpartition(":")
            algo = algo or "sha256"
            want_len = hashlib.new(algo).digest_size * 2
            assert re.fullmatch(rf"[0-9a-f]{{{want_len}}}", hexval), (
                f"{name}/{fname}: malformed {algo} pin {digest!r}")


def test_download_refuses_unpinned_builtin(tmp_path, monkeypatch):
    """A built-in recipe that loses its pin must refuse to download at
    all — only caller-supplied urls= may skip verification."""
    import split_learning_tpu.data.datasets as dsm
    monkeypatch.setitem(
        dsm._DOWNLOADS, "mnist",
        [("f.gz", "https://unreachable.invalid/f.gz", None)])
    with pytest.raises(ChecksumError, match="no pinned digest"):
        download_dataset("mnist", str(tmp_path / "d"))


def test_download_verifies_md5_prefixed_pin(tmp_path, idx_http_server):
    """'md5:<hex>' pins verify with md5 (the CIFAR-10 publisher only
    posts md5); mismatches carry the computed sha256 for upgrading."""
    import hashlib
    import urllib.request
    base, sums = idx_http_server
    specs = _specs(base, sums)
    with urllib.request.urlopen(specs[0][1]) as r:
        good_md5 = hashlib.md5(r.read()).hexdigest()
    one = [(specs[0][0], specs[0][1], f"md5:{good_md5}")]
    assert len(download_dataset("mnist", str(tmp_path / "a"), urls=one)) == 1
    bad = [(specs[0][0], specs[0][1], "md5:" + "0" * 32)]
    with pytest.raises(ChecksumError, match="md5 mismatch"):
        download_dataset("mnist", str(tmp_path / "b"), urls=bad)


def test_download_unpinned_accepts_and_logs(tmp_path, idx_http_server,
                                            capsys):
    base, sums = idx_http_server
    specs = [(n, u, None) for n, u, _ in _specs(base, sums)]
    fetched = download_dataset("mnist", str(tmp_path / "d"), urls=specs)
    assert len(fetched) == 4
    err = capsys.readouterr().err
    assert "unpinned" in err and list(sums.values())[0] in err
