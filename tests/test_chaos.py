"""Chaos wire, replay cache, and circuit breaker: deterministic fault
injection (transport/chaos.py), exactly-once recovery of a response lost
after server apply (the desync the reference cannot survive), breaker
state machine, and backoff schedules. All fast — no real sleeps, tiny
models — so CI can run this file as the fault-tolerance smoke."""

import threading
import time

import jax
import numpy as np
import pytest

from split_learning_tpu.models import get_plan
from split_learning_tpu.runtime import (
    ReplayCache, ServerRuntime, SplitClientTrainer)
from split_learning_tpu.runtime.breaker import (
    CLOSED, HALF_OPEN, OPEN, CircuitBreaker)
from split_learning_tpu.runtime.client import FailurePolicy
from split_learning_tpu.transport import (
    ChaosPolicy, ChaosTransport, LocalTransport, TransportError)
from split_learning_tpu.transport.base import backoff_delays
from split_learning_tpu.transport.chaos import parse_chaos_spec
from split_learning_tpu.transport.codec import TopK8EF, topk8_compress
from split_learning_tpu.transport.http import HttpTransport, SplitHTTPServer
from split_learning_tpu.utils import Config

BATCH = 8


def _runtime():
    cfg = Config(mode="split", batch_size=BATCH)
    plan = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    return cfg, plan, ServerRuntime(plan, cfg, jax.random.PRNGKey(2),
                                    sample)


# ---------------------------------------------------------------------- #
# spec grammar + schedule determinism
# ---------------------------------------------------------------------- #

def test_parse_chaos_spec_grammar():
    f = parse_chaos_spec("drop_resp=0.1,dup,delay=0.02:250")
    assert list(f) == ["drop_resp", "dup", "delay"]  # order preserved
    assert f["drop_resp"] == (0.1, 50.0)   # default delay arg unused
    assert f["dup"][0] == 0.05             # DEFAULT_RATE
    assert f["delay"] == (0.02, 250.0)

    with pytest.raises(ValueError, match="unknown chaos kind"):
        parse_chaos_spec("drop_response=0.1")
    with pytest.raises(ValueError, match="bad chaos rate"):
        parse_chaos_spec("dup=lots")
    with pytest.raises(ValueError, match="in \\[0, 1\\]"):
        parse_chaos_spec("dup=1.5")
    with pytest.raises(ValueError, match="sum to > 1"):
        parse_chaos_spec("dup=0.6,drop_resp=0.6")


def test_chaos_policy_schedule_is_seeded_and_bounded():
    """Same (spec, seed) = the same faults at the same (path, step,
    attempt) keys — a chaotic run is exactly reproducible — and every
    key goes clean at attempt >= max_faults_per_key."""
    spec = "drop_resp=0.15,dup=0.1,http500=0.05"
    keys = [(p, s, a) for p in ("/forward_pass", "/u_backward")
            for s in range(60) for a in range(3)]
    a = ChaosPolicy(spec, seed=7)
    b = ChaosPolicy(spec, seed=7)
    sched_a = [a.draw(*k) for k in keys]
    assert sched_a == [b.draw(*k) for k in keys]
    assert any(f is not None for f in sched_a)
    assert sched_a != [ChaosPolicy(spec, seed=8).draw(*k) for k in keys]
    # bounded chaos: attempt 2 is clean for every key (max_faults=2),
    # so RETRY with max_retries >= 2 always completes the step
    assert all(a.draw(p, s, 2) is None
               for p in ("/forward_pass",) for s in range(200))


def test_chaos_off_path_is_bitwise_legacy():
    """A zero-rate policy injects nothing and perturbs nothing: a
    chaos-wrapped twin trains bit-identically to the bare transport
    (and the CLI never even constructs the wrapper without --chaos)."""
    runs = {}
    for wrap in (False, True):
        cfg, plan, runtime = _runtime()
        transport = LocalTransport(runtime)
        if wrap:
            transport = ChaosTransport(
                transport, ChaosPolicy("drop_resp=0.0", seed=3))
        client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(2),
                                    transport)
        rs = np.random.RandomState(5)
        losses = []
        for step in range(5):
            x = rs.randn(BATCH, 28, 28, 1).astype(np.float32)
            y = rs.randint(0, 10, (BATCH,)).astype(np.int64)
            losses.append(client.train_step(x, y, step))
        runs[wrap] = losses
    assert runs[True] == runs[False]


# ---------------------------------------------------------------------- #
# the killer case: response lost AFTER the server applied the update
# ---------------------------------------------------------------------- #

def test_lost_response_recovered_bit_identical_over_http():
    """Regression for the reference's silent desync: the server applies
    step N, the reply dies on the wire, the client retries N. Without
    the replay cache the retry would either 409 (strict steps) or apply
    N twice; with it, the retry is served the *original bytes*."""
    cfg, plan, runtime = _runtime()
    # drop_resp=1.0 with max_faults_per_key=2: attempts 0 and 1 lose
    # the reply (after apply/cache), attempt 2 is clean
    server = SplitHTTPServer(
        runtime, chaos=ChaosPolicy("drop_resp=1.0", seed=0)).start()
    transport = HttpTransport(server.url)
    # a fault-free twin: what the bytes *should* decode to
    cfg2, plan2, runtime2 = _runtime()
    clean_srv = SplitHTTPServer(runtime2).start()
    clean = HttpTransport(clean_srv.url)
    try:
        rs = np.random.RandomState(4)
        acts = rs.randn(BATCH, 26, 26, 32).astype(np.float32)
        labels = rs.randint(0, 10, (BATCH,)).astype(np.int64)
        with pytest.raises(TransportError):
            transport.split_step(acts, labels, 0)   # applied, reply lost
        assert runtime.health()["step"] == 0        # it DID apply
        with pytest.raises(TransportError):
            transport.split_step(acts, labels, 0)   # cached reply lost too
        g, loss = transport.split_step(acts, labels, 0)  # clean attempt
        g_ref, loss_ref = clean.split_step(acts, labels, 0)
        np.testing.assert_array_equal(g, g_ref)
        assert loss == loss_ref
        assert runtime.health()["step"] == 0        # applied exactly once
        assert runtime.replay.body_hits >= 1        # original bytes reused
    finally:
        transport.close()
        clean.close()
        server.stop()
        clean_srv.stop()


def test_trainer_retry_survives_server_chaos_without_losing_batches():
    """Satellite regression: SplitClientTrainer on RETRY + a chaotic
    server = zero dropped batches and finite losses, end to end."""
    cfg, plan, runtime = _runtime()
    server = SplitHTTPServer(
        runtime,
        chaos=ChaosPolicy("drop_resp=0.3,http500=0.2", seed=11)).start()
    transport = HttpTransport(server.url)
    try:
        client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(2),
                                    transport,
                                    failure_policy=FailurePolicy.RETRY,
                                    max_retries=3, retry_backoff=0.0)
        rs = np.random.RandomState(6)
        for step in range(12):
            x = rs.randn(BATCH, 28, 28, 1).astype(np.float32)
            y = rs.randint(0, 10, (BATCH,)).astype(np.int64)
            assert np.isfinite(client.train_step(x, y, step))
        assert client.dropped_batches == 0
        assert runtime.health()["step"] == 11
        assert sum(server.chaos.injected.values()) > 0
    finally:
        transport.close()
        server.stop()


def test_client_side_dup_served_from_replay_cache():
    """ChaosTransport dup delivers twice; the duplicate must come back
    from the server's replay cache bit-equal, with one apply."""
    cfg, plan, runtime = _runtime()
    transport = ChaosTransport(LocalTransport(runtime),
                               ChaosPolicy("dup=1.0", seed=0))
    g, loss = transport.split_step(
        np.ones((BATCH, 26, 26, 32), np.float32),
        np.zeros((BATCH,), np.int64), 0)
    assert np.all(np.isfinite(g))
    assert runtime.health()["step"] == 0
    assert runtime.replay.hits >= 1
    assert transport.stats.counters.get("chaos_dup") == 1


# ---------------------------------------------------------------------- #
# replay cache unit behaviour
# ---------------------------------------------------------------------- #

def test_replay_cache_first_apply_wins_and_evicts():
    rc = ReplayCache(window=2, max_total=64)
    rc.put(0, "split_step", 1, "first")
    rc.put(0, "split_step", 1, "second")          # duplicate apply race
    assert rc.get(0, "split_step", 1) == "first"  # original wins
    rc.attach_body(0, "split_step", 1, b"bytes")
    rc.attach_body(0, "split_step", 1, b"other")  # body is set-once too
    assert rc.get_body(0, "split_step", 1) == b"bytes"
    rc.put(0, "split_step", 2, "r2")
    rc.put(0, "split_step", 3, "r3")              # window=2: evicts step 1
    assert rc.get(0, "split_step", 1) is None
    assert rc.get(1, "split_step", 1) is None     # other client: miss
    c = rc.counters()
    assert c["replay_evictions"] == 1
    assert c["replay_cache_size"] == 2
    rc.clear()
    assert rc.counters()["replay_cache_size"] == 0


# ---------------------------------------------------------------------- #
# breaker + backoff
# ---------------------------------------------------------------------- #

def test_backoff_delays_schedule_and_jitter():
    gen = backoff_delays(0.5, jitter=0.0)
    assert [next(gen) for _ in range(6)] == [0.5, 1.0, 2.0, 4.0, 5.0, 5.0]
    # seeded jitter is deterministic and bounded to [d, d * (1+jitter)]
    g1 = backoff_delays(0.5, jitter=0.5, rng=np.random.RandomState(0))
    g2 = backoff_delays(0.5, jitter=0.5, rng=np.random.RandomState(0))
    d1 = [next(g1) for _ in range(6)]
    assert d1 == [next(g2) for _ in range(6)]
    for base, d in zip([0.5, 1.0, 2.0, 4.0, 5.0, 5.0], d1):
        assert base <= d <= base * 1.5


def test_circuit_breaker_state_machine():
    up = {"ok": True}

    def probe():
        if not up["ok"]:
            raise TransportError("down")
        return {"status": "healthy"}

    slept = []
    br = CircuitBreaker(probe, failure_threshold=3, probe_jitter=0.0,
                        seed=0, sleep=slept.append)
    assert br.state == CLOSED
    br.before_attempt()                      # closed: free pass, no sleep
    assert slept == []
    br.record_failure()
    br.record_failure()
    assert br.state == CLOSED                # below threshold
    br.record_failure()
    assert br.state == OPEN
    assert br.counters["breaker_opened"] == 1
    br.before_attempt()                      # probe succeeds immediately
    assert br.state == HALF_OPEN
    assert br.counters["breaker_probes"] == 1
    assert slept == [0.5]                    # one backoff sleep, no jitter
    br.record_failure()                      # the trial request failed
    assert br.state == OPEN
    assert br.counters["breaker_reopened"] == 1
    br.before_attempt()
    assert br.state == HALF_OPEN
    br.record_success()                      # trial succeeded: re-close
    assert br.state == CLOSED
    assert br.counters["breaker_reclosed"] == 1


def test_circuit_breaker_gives_up_after_max_open_s():
    def dead():
        raise TransportError("down forever")

    br = CircuitBreaker(dead, failure_threshold=1, max_open_s=0.0,
                        probe_jitter=0.0, sleep=lambda _s: None)
    br.record_failure()
    assert br.state == OPEN
    with pytest.raises(TransportError, match="circuit open"):
        br.before_attempt()
    assert br.counters["breaker_probe_failures"] >= 1


# ---------------------------------------------------------------------- #
# EF rollback/replay consistency
# ---------------------------------------------------------------------- #

def test_ef_rollback_then_repack_is_bit_identical():
    """The invariant the HTTP retry path and the server's cached-result
    replay both lean on: rollback restores the exact pre-compress
    residual, so re-packing the same tensor reproduces the same wire
    dict bit for bit — a replayed delivery and a retried send carry
    identical payloads."""
    ef = TopK8EF()
    arr = np.random.RandomState(0).randn(64, 64).astype(np.float32)
    warm = np.random.RandomState(1).randn(64, 64).astype(np.float32)
    ef.compress("k", warm, 0.05)             # leave a non-zero residual
    p1 = ef.compress("k", arr, 0.05)
    ef.rollback("k")
    p2 = ef.compress("k", arr, 0.05)
    assert p1.keys() == p2.keys()
    for key in p1:
        if isinstance(p1[key], np.ndarray):
            np.testing.assert_array_equal(p1[key], p2[key])
        else:
            assert p1[key] == p2[key]
    # and the stateless core is itself deterministic
    d1, r1 = topk8_compress(arr, 0.05)
    d2, r2 = topk8_compress(arr, 0.05)
    np.testing.assert_array_equal(d1["q"], d2["q"])
    np.testing.assert_array_equal(r1, r2)


# ---------------------------------------------------------------------- #
# async dispatch (PR 5): exactly-once across the off-lock window
# ---------------------------------------------------------------------- #

def test_duplicate_during_materialization_blocks_on_inflight_future():
    """Async dispatch opens a window the old cache could not cover: the
    step is applied but its reply is still materializing off the lock.
    A duplicate landing there must block on the in-flight future and be
    served the ONE materialized reply — not 409 (the step is not a
    stale replay) and not a second apply."""
    cfg = Config(mode="split", batch_size=BATCH)
    plan = get_plan(mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    runtime = ServerRuntime(plan, cfg, jax.random.PRNGKey(2), sample,
                            overlap=True, d2h_delay_s=0.4)
    rs = np.random.RandomState(0)
    x = rs.randn(BATCH, 26, 26, 32).astype(np.float32)  # cut-layer acts
    y = rs.randint(0, 10, BATCH).astype(np.int64)
    runtime.split_step(x, y, 0)  # compile + one padded materialization

    results = {}
    ta = threading.Thread(
        target=lambda: results.update(a=runtime.split_step(x, y, 1)))
    ta.start()
    time.sleep(0.15)  # the original is now materializing, off the lock
    t0 = time.perf_counter()
    res_b = runtime.split_step(x, y, 1)  # duplicate delivery
    waited = time.perf_counter() - t0
    ta.join()
    res_a = results["a"]

    assert waited > 0.05  # it really blocked on the in-flight future
    np.testing.assert_array_equal(res_b[0], res_a[0])  # identical reply
    assert res_b[1] == res_a[1]
    assert runtime.replay.hits == 1       # served from the future, once
    assert runtime.health()["step"] == 1
    assert int(runtime.state.step) == 2   # warmup + ONE apply, not two
