"""Numerical equivalence: split (2-party) and U-shaped (3-hop) training must
match monolithic training exactly (SURVEY.md §4 item 3 — the property the
reference only eyeballs via MLflow loss curves).

Key fact making this exact: SGD without momentum updates each parameter
independently, so per-stage optimizers ≡ one joint optimizer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from split_learning_tpu.core import cross_entropy
from split_learning_tpu.models import get_plan
from split_learning_tpu.runtime import (
    FederatedClientTrainer, ServerRuntime, SplitClientTrainer,
    USplitClientTrainer, apply_grads, make_state, sgd)
from split_learning_tpu.transport import LocalTransport
from split_learning_tpu.utils import Config

SEED = 42
N_STEPS = 8
BATCH = 16


def data_stream():
    rs = np.random.RandomState(123)
    batches = []
    for _ in range(N_STEPS):
        x = rs.randn(BATCH, 28, 28, 1).astype(np.float32)
        y = (rs.randint(0, 10, (BATCH,))).astype(np.int64)
        batches.append((x, y))
    return batches


def monolithic_losses(mode="split"):
    """Ground truth: jointly train the full composition with one SGD."""
    plan = get_plan(mode=mode)
    batches = data_stream()
    params = tuple(plan.init(jax.random.PRNGKey(SEED),
                             jnp.asarray(batches[0][0])))
    tx = sgd(0.01)
    state = make_state(params, tx)

    @jax.jit
    def step(state, x, y):
        def loss_fn(p):
            return cross_entropy(plan.apply(p, x), y)
        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return apply_grads(tx, state, grads), loss

    losses = []
    for x, y in batches:
        state, loss = step(state, jnp.asarray(x), jnp.asarray(y))
        losses.append(float(loss))
    return np.asarray(losses), state.params


def test_split_equals_monolithic():
    cfg = Config(mode="split", batch_size=BATCH, lr=0.01)
    plan = get_plan(mode="split")
    batches = data_stream()
    # both parties share the init seed (see SplitClientTrainer.ensure_init)
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(SEED), batches[0][0])
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(SEED),
                                LocalTransport(server, through_codec=True))
    split_losses = []
    for step, (x, y) in enumerate(batches):
        split_losses.append(client.train_step(x, y, step))

    mono_losses, mono_params = monolithic_losses()
    np.testing.assert_allclose(split_losses, mono_losses, rtol=1e-5, atol=1e-6)
    # final params of both halves must match too
    flat_split = jax.tree_util.tree_leaves(
        (client.state.params, server.state.params))
    flat_mono = jax.tree_util.tree_leaves(mono_params)
    for a, b in zip(flat_split, flat_mono):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_u_split_equals_monolithic():
    """3-hop U-shaped training (labels never leave the client) trains the
    same function as the monolithic model (BASELINE.md config 5)."""
    cfg = Config(mode="u_split", batch_size=BATCH, lr=0.01)
    plan = get_plan(mode="u_split")
    batches = data_stream()
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(SEED), batches[0][0])
    client = USplitClientTrainer(plan, cfg, jax.random.PRNGKey(SEED),
                                 LocalTransport(server))
    u_losses = []
    for step, (x, y) in enumerate(batches):
        u_losses.append(client.train_step(x, y, step))

    mono_losses, _ = monolithic_losses(mode="u_split")
    np.testing.assert_allclose(u_losses, mono_losses, rtol=1e-5, atol=1e-6)


def test_federated_single_client_equals_local_training():
    """With one client, FedAvg degenerates to the reference's overwrite
    (src/server_part.py:81-83) — federated training ≡ plain local training."""
    cfg = Config(mode="federated", batch_size=BATCH, lr=0.01, epochs=2)
    plan = get_plan(mode="federated")
    batches = data_stream()
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(SEED), batches[0][0])
    client = FederatedClientTrainer(plan, cfg, jax.random.PRNGKey(SEED),
                                    LocalTransport(server))
    records = client.train(lambda: iter(batches), epochs=2)
    assert len(records) == 2  # one record per epoch

    # plain local training, same data, same seed
    mono_plan = get_plan(mode="federated")
    params = tuple(mono_plan.init(jax.random.PRNGKey(SEED),
                                  jnp.asarray(batches[0][0])))
    tx = sgd(0.01)
    state = make_state(params, tx)

    @jax.jit
    def step(state, x, y):
        def loss_fn(p):
            return cross_entropy(mono_plan.apply(p, x), y)
        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return apply_grads(tx, state, grads), loss

    for _ in range(2):
        for x, y in batches:
            state, _ = step(state, jnp.asarray(x), jnp.asarray(y))

    for a, b in zip(jax.tree_util.tree_leaves(client.state.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
