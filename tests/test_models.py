"""Stage math: shapes, parameter counts, factory dispatch (SURVEY.md §4 item 1).

Derived facts from the reference (SURVEY.md §2): PartA = 320 params,
PartB = 110,666, full = 110,986; cut tensor [64, 26, 26, 32] (NHWC).
"""

import jax
import jax.numpy as jnp
import pytest

from split_learning_tpu.models import get_model, get_plan
from split_learning_tpu.models.cnn import split_cnn_plan, u_split_cnn_plan


def n_params(tree):
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def test_split_cnn_shapes_and_param_counts(rng, mnist_batch):
    x, _ = mnist_batch
    plan = split_cnn_plan()
    params = plan.init(rng, x)

    acts = plan.stages[0].apply(params[0], x)
    assert acts.shape == (64, 26, 26, 32)  # cut-layer tensor, 5.28 MiB fp32
    assert acts.dtype == jnp.float32

    logits = plan.stages[1].apply(params[1], acts)
    assert logits.shape == (64, 10)

    assert n_params(params[0]) == 320
    assert n_params(params[1]) == 110_666
    assert n_params(params) == 110_986


def test_u_split_preserves_total_params(rng, mnist_batch):
    x, _ = mnist_batch
    plan = u_split_cnn_plan()
    params = plan.init(rng, x)
    assert plan.num_stages == 3
    assert plan.owners == ("client", "server", "client")
    assert n_params(params) == 110_986
    logits = plan.apply(params, x)
    assert logits.shape == (64, 10)


def test_composition_equals_stagewise(rng, mnist_batch):
    """FullModel ≡ composition of stages, by construction (ref src/model_def.py:31-46)."""
    x, _ = mnist_batch
    plan = split_cnn_plan()
    params = plan.init(rng, x)
    full = plan.apply(params, x)
    staged = plan.stages[1].apply(params[1], plan.stages[0].apply(params[0], x))
    assert jnp.array_equal(full, staged)


def test_factory_dispatch():
    # mirrors get_model role/mode dispatch (ref src/model_def.py:49-71)
    plan, owned = get_model("client", mode="split")
    assert owned == (0,)
    plan, owned = get_model("server", mode="split")
    assert owned == (1,)
    plan, owned = get_model("client", mode="federated")
    assert owned == (0, 1)
    plan, owned = get_model("client", mode="u_split")
    assert owned == (0, 2)
    plan, owned = get_model("server", mode="u_split")
    assert owned == (1,)


def test_factory_rejects_unknown_mode_and_role():
    # ValueError contract (ref src/model_def.py:70-71, src/client_part.py:208-209)
    with pytest.raises(ValueError):
        get_model("client", mode="quantum")
    with pytest.raises(ValueError):
        get_model("supervisor", mode="split")
    with pytest.raises(ValueError):
        get_plan(model="not_a_model")


def test_config_env_parsing(monkeypatch):
    from split_learning_tpu.utils import Config
    cfg = Config.from_env(env={"LEARNING_MODE": "federated", "SLT_BATCH_SIZE": "32"})
    assert cfg.mode == "federated"
    assert cfg.batch_size == 32
    cfg2 = Config.from_env(env={}, mode="split", lr=0.1)
    assert cfg2.lr == 0.1
    with pytest.raises(ValueError):
        Config.from_env(env={"LEARNING_MODE": "bogus"})
