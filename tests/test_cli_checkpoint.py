"""CLI checkpoint/resume and eval — the reference persists nothing
(SURVEY.md §5 'Checkpoint / resume'); here save → resume → eval must work
end-to-end through the launcher for every checkpointable topology."""

import json
import os

import pytest

from split_learning_tpu.launch.run import main


def _train(tmp_path, ckdir, *extra):
    return main(["train", "--dataset", "synthetic", "--steps", "4",
                 "--batch-size", "16", "--epochs", "1",
                 "--data-dir", str(tmp_path), "--tracking", "noop",
                 "--checkpoint-dir", str(ckdir), *extra])


@pytest.mark.parametrize("mode,transport", [
    ("split", "fused"),
    ("split", "local"),
    ("u_split", "local"),
    ("federated", "local"),
])
@pytest.mark.slow
def test_checkpoint_resume_eval(tmp_path, capsys, mode, transport):
    ck = tmp_path / "ckpt"
    assert _train(tmp_path, ck, "--mode", mode,
                  "--transport", transport) == 0
    assert (ck / "meta.json").exists()

    # resume continues from the saved step
    assert _train(tmp_path, ck, "--mode", mode, "--transport", transport,
                  "--resume") == 0
    err = capsys.readouterr().err
    assert "resumed at step 4" in err

    # standalone eval reassembles the full composition from the checkpoint
    assert main(["eval", "--checkpoint-dir", str(ck),
                 "--data-dir", str(tmp_path), "--batch-size", "64"]) == 0
    out = capsys.readouterr().out
    line = [l for l in out.splitlines() if l.startswith("{")][-1]
    res = json.loads(line)
    assert res["checkpoint_step"] == 8
    assert 0.0 <= res["accuracy"] <= 1.0
    assert res["examples"] > 0


def test_checkpoint_every_fused(tmp_path, capsys):
    ck = tmp_path / "ck2"
    assert _train(tmp_path, ck, "--mode", "split", "--transport", "fused",
                  "--checkpoint-every", "2") == 0
    from split_learning_tpu.runtime.checkpoint import Checkpointer
    steps = list(Checkpointer(str(ck)).all_steps())
    assert 2 in steps and 4 in steps


def test_train_eval_flag(tmp_path, capsys):
    assert main(["train", "--dataset", "synthetic", "--steps", "3",
                 "--batch-size", "16", "--epochs", "1",
                 "--data-dir", str(tmp_path), "--tracking", "noop",
                 "--transport", "fused", "--eval"]) == 0
    out = capsys.readouterr().out
    assert "[eval] accuracy=" in out


def _start_http_server(cfg_kwargs, ckdir=None, resume=False, every=1):
    import jax
    import numpy as np
    from split_learning_tpu.models import get_plan
    from split_learning_tpu.runtime import ServerRuntime
    from split_learning_tpu.runtime.checkpoint import Checkpointer
    from split_learning_tpu.transport.http import SplitHTTPServer
    from split_learning_tpu.utils import Config

    cfg = Config(**cfg_kwargs)
    plan = get_plan(mode=cfg.mode)
    sample = np.zeros((cfg.batch_size, 28, 28, 1), np.float32)
    runtime = ServerRuntime(plan, cfg, jax.random.PRNGKey(cfg.seed), sample)
    if ckdir is not None:
        ckptr = Checkpointer(str(ckdir))
        latest = ckptr.latest_step()
        if resume and latest is not None:
            runtime.resume_from(
                ckptr.restore({"server": runtime.state})["server"], latest)

        def on_step(step):
            if (step + 1) % every == 0 and ckptr.latest_step() != step + 1:
                ckptr.save(step + 1, {"server": runtime.state})

        runtime.on_step = on_step
    return SplitHTTPServer(runtime).start()


def test_http_resume_guard_rejects_fresh_server(tmp_path, capsys):
    """A resumed client must refuse to train against a server that was not
    resumed (the silent-desync hazard, SURVEY.md §3.4)."""
    ck = tmp_path / "ck_http"
    server = _start_http_server({"mode": "split", "batch_size": 16})
    try:
        assert _train(tmp_path, ck, "--mode", "split", "--transport", "http",
                      "--server-url", server.url) == 0
    finally:
        server.stop()
    # fresh (un-resumed) server: health step == -1 < checkpoint step
    server2 = _start_http_server({"mode": "split", "batch_size": 16})
    try:
        rc = _train(tmp_path, ck, "--mode", "split", "--transport", "http",
                    "--server-url", server2.url, "--resume")
        assert rc == 3
        assert "was not resumed" in capsys.readouterr().err
    finally:
        server2.stop()


@pytest.mark.slow
def test_http_resume_both_halves(tmp_path, capsys):
    """Server checkpoints via on_step; a restarted resumed pair trains on."""
    ck_c = tmp_path / "ck_client"
    ck_s = tmp_path / "ck_server"
    server = _start_http_server({"mode": "split", "batch_size": 16},
                                ckdir=ck_s, every=1)
    try:
        assert _train(tmp_path, ck_c, "--mode", "split", "--transport",
                      "http", "--server-url", server.url) == 0
    finally:
        server.stop()
    # both parties restart and resume; handshake floor accepts the client
    server2 = _start_http_server({"mode": "split", "batch_size": 16},
                                 ckdir=ck_s, resume=True)
    try:
        assert _train(tmp_path, ck_c, "--mode", "split", "--transport",
                      "http", "--server-url", server2.url, "--resume") == 0
        assert "resumed at step 4" in capsys.readouterr().err
    finally:
        server2.stop()


def test_resume_rearms_server_handshake(tmp_path, capsys):
    """After resume the local server refuses steps below the floor —
    exercised implicitly: resumed training starts at the restored step and
    must be accepted."""
    ck = tmp_path / "ck3"
    assert _train(tmp_path, ck, "--mode", "split", "--transport", "local") == 0
    assert _train(tmp_path, ck, "--mode", "split", "--transport", "local",
                  "--resume") == 0
    out = capsys.readouterr().out
    assert out.count("[done]") >= 1


@pytest.mark.slow
def test_checkpoint_resume_eval_transformer(tmp_path, capsys):
    """The long-context family checkpoints/resumes/evals through the same
    machinery (token dataset, fused transport)."""
    ck = tmp_path / "ck_tfm"
    base = ["--mode", "split", "--transport", "fused",
            "--model", "transformer", "--dataset", "tokens"]
    assert _train(tmp_path, ck, *base) == 0
    assert _train(tmp_path, ck, *base, "--resume") == 0
    assert "resumed at step 4" in capsys.readouterr().err
    assert main(["eval", "--checkpoint-dir", str(ck),
                 "--data-dir", str(tmp_path), "--batch-size", "64"]) == 0
    out = capsys.readouterr().out
    res = json.loads([l for l in out.splitlines()
                      if l.startswith("{")][-1])
    assert res["checkpoint_step"] == 8
    assert 0.0 <= res["accuracy"] <= 1.0


@pytest.mark.slow
def test_serve_resume_from_joint_checkpoint(tmp_path, capsys):
    """`serve --resume` on a JOINT checkpoint dir (written by local/fused
    training) must restore the server subtree, leave the joint meta.json
    untouched (periodic saves go to a server_party/ subdir), and yield
    remote-eval metrics identical to local full-composition eval."""
    import subprocess
    import sys as _sys

    ck = tmp_path / "joint"
    assert _train(tmp_path, ck, "--mode", "split",
                  "--transport", "local") == 0
    assert main(["eval", "--checkpoint-dir", str(ck),
                 "--data-dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    local = json.loads([l for l in out.splitlines()
                        if l.startswith("{")][-1])

    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    port = "18791"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    srv = subprocess.Popen(
        [_sys.executable, "-m", "split_learning_tpu.launch.run", "serve",
         "--mode", "split", "--host", "127.0.0.1", "--port", port,
         "--checkpoint-dir", str(ck), "--resume",
         "--data-dir", str(tmp_path)],
        env=env, cwd=repo, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        assert main(["eval", "--checkpoint-dir", str(ck),
                     "--data-dir", str(tmp_path),
                     "--server-url", f"http://127.0.0.1:{port}"]) == 0
        out = capsys.readouterr().out
        remote = json.loads([l for l in out.splitlines()
                             if l.startswith("{")][-1])
    finally:
        srv.terminate()
        srv.wait(timeout=30)

    assert remote["accuracy"] == local["accuracy"]
    assert abs(remote["loss"] - local["loss"]) < 1e-3
    meta = json.loads((ck / "meta.json").read_text())
    assert meta["layout"] == "split_local"  # not clobbered to server_only


def test_reconcile_sizes_accepts_explicit_defaults():
    """ADVICE r4: explicit size flags that restate the builder's
    defaults against a default-size checkpoint (and vice versa) must be
    accepted — saved and requested sizes compare as *effective* plans,
    merged over the builder signature's defaults. Only a flag that
    would rebuild a different plan refuses."""
    from split_learning_tpu.launch.run import _reconcile_ckpt_sizes

    # default-size checkpoint (no size_kw persisted) + flags == defaults
    kw, seq, err = _reconcile_ckpt_sizes(
        {}, {"d_model": 64, "num_heads": 4}, None, "eval",
        model="transformer")
    assert err is None and kw == {}

    # sized checkpoint + explicit flags restating the same values
    meta = {"size_kw": {"d_model": 256, "num_heads": 2}}
    kw, seq, err = _reconcile_ckpt_sizes(
        meta, {"d_model": 256, "num_heads": 2}, None, "eval",
        model="transformer")
    assert err is None and kw == {"d_model": 256, "num_heads": 2}

    # a flag subset whose values match the saved ones
    kw, seq, err = _reconcile_ckpt_sizes(
        meta, {"d_model": 256}, None, "eval", model="transformer")
    assert err is None and kw == {"d_model": 256, "num_heads": 2}

    # genuinely different plan still refuses, naming the conflict
    kw, seq, err = _reconcile_ckpt_sizes(
        meta, {"d_model": 128}, None, "eval", model="transformer")
    assert err is not None and "d_model" in err

    # default-size checkpoint + non-default flag refuses too (the
    # saved plan was built at d_model=64)
    kw, seq, err = _reconcile_ckpt_sizes(
        {}, {"d_model": 128}, None, "eval", model="transformer")
    assert err is not None and "d_model" in err
