"""GPipe ppermute pipeline: numerical equivalence with monolithic training
on the 8-device virtual mesh (configs 2, 4, 5 groundwork)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from split_learning_tpu.models import get_plan
from split_learning_tpu.parallel import make_mesh
from split_learning_tpu.parallel.pipeline import PipelinedTrainer
from split_learning_tpu.runtime.fused import FusedSplitTrainer
from split_learning_tpu.utils import Config

SEED = 11
BATCH = 16
N_STEPS = 4


def batches():
    rs = np.random.RandomState(5)
    return [(rs.randn(BATCH, 28, 28, 1).astype(np.float32),
             rs.randint(0, 10, (BATCH,)).astype(np.int64))
            for _ in range(N_STEPS)]


@pytest.mark.slow
@pytest.mark.parametrize("microbatches", [1, 4])
def test_two_stage_pipeline_matches_fused(devices, microbatches):
    """Config 2: split CNN as a 2-stage ppermute pipeline == fused single
    program (and hence == the HTTP-style MPMD path, by transitivity)."""
    cfg = Config(mode="split", batch_size=BATCH, microbatches=microbatches)
    plan = get_plan(mode="split")
    data = batches()

    mesh = make_mesh(num_clients=1, num_stages=2, devices=devices[:2])
    pipe = PipelinedTrainer(plan, cfg, jax.random.PRNGKey(SEED),
                            data[0][0], mesh)
    pipe_losses = [pipe.train_step(x, y) for x, y in data]

    ref = FusedSplitTrainer(plan, Config(mode="split", batch_size=BATCH),
                            jax.random.PRNGKey(SEED), data[0][0])
    ref_losses = [ref.train_step(x, y) for x, y in data]

    np.testing.assert_allclose(pipe_losses, ref_losses, rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(pipe.params),
                    jax.tree_util.tree_leaves(ref.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_three_stage_u_pipeline(devices):
    """Config 5 on the mesh: the U-shaped plan as a 3-stage pipeline."""
    cfg = Config(mode="u_split", batch_size=BATCH, microbatches=2)
    plan = get_plan(mode="u_split")
    data = batches()
    mesh = make_mesh(num_clients=1, num_stages=3, devices=devices[:3])
    pipe = PipelinedTrainer(plan, cfg, jax.random.PRNGKey(SEED),
                            data[0][0], mesh)
    losses = [pipe.train_step(x, y) for x, y in data]

    ref = FusedSplitTrainer(plan, Config(mode="u_split", batch_size=BATCH),
                            jax.random.PRNGKey(SEED), data[0][0])
    ref_losses = [ref.train_step(x, y) for x, y in data]
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_pipeline_with_data_parallel(devices):
    """Configs 2+3 composed: 2 data rows x 2 pipe stages on 4 devices."""
    cfg = Config(mode="split", batch_size=BATCH, num_clients=2, microbatches=2)
    plan = get_plan(mode="split")
    data = batches()
    mesh = make_mesh(num_clients=2, num_stages=2, devices=devices[:4])
    pipe = PipelinedTrainer(plan, cfg, jax.random.PRNGKey(SEED),
                            data[0][0], mesh)
    losses = [pipe.train_step(x, y) for x, y in data]

    ref = FusedSplitTrainer(plan, Config(mode="split", batch_size=BATCH),
                            jax.random.PRNGKey(SEED), data[0][0])
    ref_losses = [ref.train_step(x, y) for x, y in data]
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-5)
