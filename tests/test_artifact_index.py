"""artifacts/README.md is the claim-to-artifact index; an artifact the
index does not mention is unreviewable evidence, and a mentioned file
that no longer exists is a dangling citation. Date-stamped series are
indexed by their stem pattern, so new dated runs don't require an
index edit."""

import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "artifacts")


def _index_text():
    with open(os.path.join(ART, "README.md")) as f:
        return f.read()


def _dateless(name: str) -> str:
    """Collapse a date-stamped artifact name to its series stem."""
    return re.sub(r"_\d{4}-\d{2}-\d{2}", "_*", name)


def test_every_artifact_is_indexed():
    text = _index_text()
    missing = []
    for name in sorted(os.listdir(ART)):
        if name == "README.md" or name.startswith("."):
            continue
        stem = _dateless(name)
        # a file is indexed if its exact name, its dated-series stem,
        # or its wildcard form appears
        date = re.search(r"\d{4}-\d{2}-\d{2}", name)
        forms = {name, stem, stem.replace("_*", "_<date>")}
        if date:
            forms.add(name.replace(date.group(0), "*"))
            # prefix form: `tpu_profile_transformer_*` covers the
            # per-shape trace family
            parts = name.split("_")
            for i in range(2, len(parts)):
                forms.add("_".join(parts[:i]) + "_*")
        if not any(f in text for f in forms):
            missing.append(name)
    assert not missing, f"artifacts not mentioned in the index: {missing}"


def test_no_dangling_exact_citations():
    """Every exact (non-wildcard) artifact filename the index cites
    must exist."""
    text = _index_text()
    cited = re.findall(r"`([\w.\-]+\.(?:json|jsonl))`", text)
    dangling = [c for c in cited
                if "*" not in c and not os.path.exists(
                    os.path.join(ART, c))]
    assert not dangling, f"index cites missing artifacts: {dangling}"
