"""K-stage MPMD split pipeline (PR 14): StageRuntime parties chained by
the GPipe-microbatched PipelineRunner.

Pins, in order: the M=1 lag=0 chain is bit-identical to driving the
same three hops sequentially by hand (the pipeline machinery adds
threads and queues, never arithmetic); the M=4 microbatched chain stays
within an absolute-nats budget of the M=1 trajectory on the same data
(the 1/M loss-hop scaling reproduces the batch-mean gradient); chaos
dup/drop on the hop wires never double-applies a weight update — the
loss series matches the clean twin bit for bit and the hop counters
still tally exactly once; a mid-run joint checkpoint (client + every
stage, per-stage extras sidecars) round-trips to the same continuation
trajectory; and the ``mpmd_pipeline`` bench leg carries its contract
fields with every gate green."""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from split_learning_tpu.models import get_plan
from split_learning_tpu.runtime.checkpoint import (
    extras_valid, read_latest_extras, write_extras)
from split_learning_tpu.runtime.pipeline_runner import PipelineRunner
from split_learning_tpu.runtime.stage import StageRuntime
from split_learning_tpu.runtime.state import (
    apply_grads, make_state, make_tx)
from split_learning_tpu.transport.chaos import ChaosPolicy, ChaosTransport
from split_learning_tpu.transport.local import LocalTransport
from split_learning_tpu.utils import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BATCH = 8
SEED = 2


def _cfg(microbatches, batch=BATCH):
    return Config(mode="split", model="split_cnn_chain3",
                  batch_size=batch, num_stages=3,
                  microbatches=microbatches, seed=SEED)


def _chain(microbatches, apply_lag, wrap=None, batch=BATCH):
    """One 3-stage chain: client stage 0 + two in-process StageRuntime
    parties, every party initialized from the same plan-level seed (the
    launch path's convention — no weights ship)."""
    cfg = _cfg(microbatches, batch)
    plan = get_plan(model="split_cnn_chain3", mode="split")
    sample = np.zeros((batch, 28, 28, 1), np.float32)
    stages = [StageRuntime(plan, i, cfg, jax.random.PRNGKey(SEED),
                           sample, microbatches=microbatches,
                           apply_lag=apply_lag)
              for i in (1, 2)]
    transports = [LocalTransport(s) for s in stages]
    if wrap is not None:
        transports = [wrap(t) for t in transports]
    runner = PipelineRunner(plan, cfg, jax.random.PRNGKey(SEED), sample,
                            transports, microbatches=microbatches)
    return runner, stages, plan


def _close(runner, stages):
    runner.close()
    for s in stages:
        s.close()


def _batch(seed, batch=BATCH):
    rs = np.random.RandomState(seed)
    return (rs.randn(batch, 28, 28, 1).astype(np.float32),
            rs.randint(0, 10, batch).astype(np.int64))


# ---------------------------------------------------------------------- #
# numerics: M=1 lag=0 bit-identity, M=4 staleness budget
# ---------------------------------------------------------------------- #

def test_m1_lag0_bit_identical_to_sequential_drive():
    """With one microbatch and no apply lag every hop blocks on the
    previous one, so the worker threads and queues are pure plumbing:
    the piped loss series must equal, bit for bit, driving identically
    seeded StageRuntimes by hand through the same three hops with the
    runner's own stage-0 arithmetic."""
    steps = 4
    runner, stages, _ = _chain(1, 0)
    try:
        piped = [runner.step(*_batch(i), i) for i in range(steps)]
    finally:
        _close(runner, stages)

    cfg = _cfg(1)
    plan = get_plan(model="split_cnn_chain3", mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    s1, s2 = (StageRuntime(plan, i, cfg, jax.random.PRNGKey(SEED),
                           sample, microbatches=1, apply_lag=0)
              for i in (1, 2))
    stage0 = plan.stages[0]
    tx = make_tx(cfg)
    state = make_state(
        plan.init(jax.random.PRNGKey(SEED), jnp.asarray(sample))[0], tx)

    # the runner's stage-0 programs, re-jitted from the same jaxprs
    fwd0 = jax.jit(lambda p, x: stage0.apply(p, x))

    def bwd_acc_fn(params, x, g, acc):
        _, vjp = jax.vjp(lambda p: stage0.apply(p, x), params)
        (gp,) = vjp(g)
        return jax.tree_util.tree_map(jnp.add, acc, gp)

    bwd_acc = jax.jit(bwd_acc_fn)
    zeros = jax.jit(
        lambda p: jax.tree_util.tree_map(jnp.zeros_like, p))

    manual = []
    try:
        for step in range(steps):
            x, y = _batch(step)
            x_dev = jnp.asarray(x)
            y0 = np.asarray(fwd0(state.params, x_dev))
            y1 = s1.hop_forward(y0, step, 0, 0)
            g1, loss = s2.hop_loss(y1, y, step, 0, 0)
            g0 = s1.hop_backward(g1, step, 0, 0)
            acc = bwd_acc(state.params, x_dev, jnp.asarray(g0),
                          zeros(state.params))
            state = jax.jit(
                lambda s, g: apply_grads(tx, s, g))(state, acc)
            manual.append(float(np.mean([loss])))
    finally:
        s1.close()
        s2.close()
    assert piped == manual
    for s in stages:
        ctr = s.counters()
        assert ctr["deferred_enqueued"] == steps
        assert ctr["deferred_applied"] == steps
        assert ctr["deferred_apply_depth"] == 0


def test_m4_stays_within_nats_budget_of_m1():
    """GPipe microbatching re-associates the gradient sum (M
    per-microbatch vjp contributions, 1/M-scaled at the loss hop) and
    lag=1 defers each stage's apply one step: same trajectory up to
    float noise and bounded staleness. Absolute-nats budget on the
    end-of-run window, same gate style as the bench leg."""
    steps = 16
    # the bench leg's converging regime: 4 fixed batches cycled at
    # batch 32 — trajectory comparisons on an oscillating tiny-batch
    # series would measure chaos, not the pipeline
    rs = np.random.RandomState(0)
    batches = [(rs.rand(32, 28, 28, 1).astype(np.float32),
                rs.randint(0, 10, 32).astype(np.int64))
               for _ in range(4)]
    runner1, stages1, _ = _chain(1, 0, batch=32)
    try:
        m1 = [runner1.step(*batches[i % 4], i) for i in range(steps)]
    finally:
        _close(runner1, stages1)
    runner4, stages4, _ = _chain(4, 1, batch=32)
    try:
        m4 = [runner4.step(*batches[i % 4], i) for i in range(steps)]
    finally:
        _close(runner4, stages4)
    gap = abs(float(np.mean(m1[-4:])) - float(np.mean(m4[-4:])))
    assert gap <= 0.35, (gap, m1, m4)


# ---------------------------------------------------------------------- #
# chaos on the hop wires: exactly-once end to end
# ---------------------------------------------------------------------- #

def test_hop_chaos_never_double_applies():
    """Dup and dropped-response faults on both hop wires: the replay
    claims make every redelivery serve the one materialized reply, so
    the loss series is BIT-identical to the clean twin, the hop
    counters tally exactly rounds x M per stage/direction, and no
    stage enqueues more than one weight update per step."""
    steps, M = 6, 2
    runner_c, stages_c, _ = _chain(M, 1)
    try:
        clean = [runner_c.step(*_batch(i), i) for i in range(steps)]
    finally:
        _close(runner_c, stages_c)

    policy = ChaosPolicy("dup=0.3,drop_resp=0.3", seed=5)
    runner_x, stages_x, _ = _chain(
        M, 1, wrap=lambda t: ChaosTransport(t, policy))
    try:
        chaotic = [runner_x.step(*_batch(i), i) for i in range(steps)]
        assert chaotic == clean
        assert sum(policy.injected.values()) > 0
        replay_hits = 0
        for s in stages_x:
            ctr = s.counters()
            for op in ("hop_fwd", "hop_bwd") if not s.is_last \
                    else ("hop_loss",):
                assert ctr[op] == steps * M, (s.party, op, ctr)
            assert ctr["deferred_enqueued"] == steps
            replay_hits += ctr["replay_hits"]
        assert replay_hits > 0  # the faults really exercised the cache
    finally:
        _close(runner_x, stages_x)


# ---------------------------------------------------------------------- #
# durability: joint checkpoint + per-stage extras round trip
# ---------------------------------------------------------------------- #

def test_mid_pipeline_checkpoint_roundtrips(tmp_path):
    """The launch path's save_chain discipline, driven directly: after
    4 steps snapshot the client state and every stage's export_state
    (which flushes that stage's deferred queue first) plus a per-stage
    extras sidecar under stage<i>/; a fresh identically-seeded chain
    that adopts the snapshot continues on the same loss trajectory bit
    for bit."""
    M, lag, ckpt_step = 2, 1, 4
    runner_a, stages_a, _ = _chain(M, lag)
    try:
        for i in range(ckpt_step):
            runner_a.step(*_batch(i), i)
        tree = {"client": runner_a.state}
        for s in stages_a:
            tree[s.party] = s.export_state()
            assert s.counters()["deferred_apply_depth"] == 0  # flushed
            d = tmp_path / s.party
            os.makedirs(d, exist_ok=True)
            write_extras(str(d), s.export_runtime_extras(ckpt_step))
        cont_a = [runner_a.step(*_batch(i), i)
                  for i in range(ckpt_step, ckpt_step + 3)]
    finally:
        _close(runner_a, stages_a)

    runner_b, stages_b, _ = _chain(M, lag)
    try:
        runner_b.state = tree["client"]
        runner_b.steps_done = ckpt_step
        for s in stages_b:
            extras = read_latest_extras(str(tmp_path / s.party),
                                        step=ckpt_step)
            assert extras is not None and extras_valid(extras)
            s.resume_from(tree[s.party], ckpt_step, extras=extras)
        cont_b = [runner_b.step(*_batch(i), i)
                  for i in range(ckpt_step, ckpt_step + 3)]
    finally:
        _close(runner_b, stages_b)
    assert cont_a == cont_b


# ---------------------------------------------------------------------- #
# bench leg contract
# ---------------------------------------------------------------------- #

@pytest.mark.slow
def test_bench_mpmd_pipeline_role_quick():
    """The mpmd_pipeline leg's contract fields (this PR): a 3-stage
    chain over synthetic heterogeneous wires, M=4 vs M=1. Gates carried
    by the leg itself: >= 1.5x microbatched speedup at equal
    byte-seconds, end-loss within the absolute-nats budget of the 1-cut
    ServerRuntime split, zero steady-state recompiles under the
    dispatch watchdog, and an exact per-stage hop tally."""
    sys.path.insert(0, REPO)
    from bench import measure_mpmd_pipeline

    mp = measure_mpmd_pipeline(quick=True)
    assert mp["leg"] == "mpmd_pipeline"
    assert mp["valid"] is True, mp["invalid_reason"]
    assert mp["stages"] == 3 and mp["microbatches"] == 4
    assert mp["model"]["family"] == "split_cnn_chain3"
    assert len(mp["one_way_latency_ms"]) == 2
    assert mp["steps_per_sec_m4"] > mp["steps_per_sec_m1"] > 0
    assert mp["pipeline_speedup"] >= 1.5
    assert mp["bubble_fraction_theoretical"] == pytest.approx(2 / 6)
    reports = mp["stage_reports_m4"]
    assert [r["stage"] for r in reports] == [1, 2]
    assert all(r["reply_p50_ms"] > 0 for r in reports)
    tally = mp["hop_tally"]
    assert len(set(tally.values())) == 1 and all(
        v > 0 for v in tally.values()), tally
    assert mp["loss_parity_nats"] <= mp["nats_budget"]
    assert mp["compile_count"]["steady_state"] == 0
