"""Device-native hop transport + 1F1B schedule (PR 16).

Pins, in order: the schedule math (both schedules share T = M+S-1 ticks
and the ideal bubble; 1F1B's warmup depth is min(S, M)); schedule
validation at the Config and runner layers; the M=1 device chain is
bit-identical to the LocalTransport chain (zero-copy relay adds no
arithmetic); 1F1B is bit-identical to GPipe at M=4 (same params
snapshot per step + microbatch-order accumulation — stronger than the
M=1-only requirement); the zero-copy pin itself — ``hop_host_copies``
stays exactly 0 across the chain and the dispatch watchdog counts no
unexpected D2H and no steady-state recompiles; ppermute parity — the
in-mesh collective path computes the same losses as the meshless relay;
the report/trace surfaces carry the schedule fields; and the SLT115
invariant actually fires on depth-bound and order violations.
"""

import os

import jax
import numpy as np
import pytest

from split_learning_tpu.models import get_plan
from split_learning_tpu.obs import dispatch_debug
from split_learning_tpu.obs import spans
from split_learning_tpu.runtime.pipeline_runner import (
    PipelineRunner, SCHEDULES, bubble_fraction, onefb_warmup,
    pipeline_ticks)
from split_learning_tpu.runtime.stage import StageRuntime
from split_learning_tpu.transport.device import DeviceTransport
from split_learning_tpu.transport.local import LocalTransport
from split_learning_tpu.utils import Config

BATCH = 8
SEED = 2


def _cfg(microbatches, schedule="gpipe"):
    return Config(mode="split", model="split_cnn_chain3",
                  batch_size=BATCH, num_stages=3,
                  microbatches=microbatches, schedule=schedule,
                  seed=SEED)


def _chain(microbatches, schedule="gpipe", transport="device",
           apply_lag=0, mesh=None):
    """One 3-stage chain: client stage 0 + two in-process StageRuntime
    parties, wired by DeviceTransport (device buffers end to end) or
    LocalTransport (the PR-14 host-numpy contract)."""
    cfg = _cfg(microbatches, schedule)
    plan = get_plan(model="split_cnn_chain3", mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    stages = [StageRuntime(plan, i, cfg, jax.random.PRNGKey(SEED),
                           sample, microbatches=microbatches,
                           apply_lag=apply_lag, mesh=mesh)
              for i in (1, 2)]
    if transport == "device":
        transports = [DeviceTransport(s, mesh=mesh) for s in stages]
    else:
        transports = [LocalTransport(s) for s in stages]
    runner = PipelineRunner(plan, cfg, jax.random.PRNGKey(SEED), sample,
                            transports, microbatches=microbatches,
                            schedule=schedule)
    return runner, stages, transports


def _close(runner, stages):
    runner.close()
    for s in stages:
        s.close()


def _batch(seed):
    rs = np.random.RandomState(seed)
    return (rs.randn(BATCH, 28, 28, 1).astype(np.float32),
            rs.randint(0, 10, BATCH).astype(np.int64))


def _losses(microbatches, schedule, transport, steps=4, mesh=None):
    runner, stages, _ = _chain(microbatches, schedule, transport,
                               mesh=mesh)
    try:
        return [runner.step(*_batch(i), i) for i in range(steps)]
    finally:
        _close(runner, stages)


# ---------------------------------------------------------------------- #
# schedule math: shared tick count/ideal bubble, 1F1B warmup depth
# ---------------------------------------------------------------------- #

def test_schedule_math():
    """Both schedules drain in T = M+S-1 ticks with ideal bubble
    (S-1)/T — 1F1B reduces in-flight DEPTH (memory), not length; the
    warmup depth is min(S, M)."""
    assert pipeline_ticks(4, 3) == 6
    assert bubble_fraction(4, 3) == pytest.approx(2 / 6)
    assert pipeline_ticks(1, 3) == 3
    assert bubble_fraction(1, 3) == pytest.approx(2 / 3)
    assert onefb_warmup(4, 3) == 3
    assert onefb_warmup(1, 3) == 1
    assert onefb_warmup(8, 3) == 3
    assert onefb_warmup(2, 5) == 2
    assert SCHEDULES == ("gpipe", "1f1b")


def test_schedule_validation():
    with pytest.raises(ValueError, match="schedule"):
        Config(mode="split", schedule="bogus")
    cfg = _cfg(1)
    plan = get_plan(model="split_cnn_chain3", mode="split")
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    with pytest.raises(ValueError, match="schedule"):
        PipelineRunner(plan, cfg, jax.random.PRNGKey(SEED), sample,
                       [object(), object()], schedule="bogus")


def test_env_knob_round_trips():
    cfg = Config.from_env(env={"SLT_SCHEDULE": "1f1b"})
    assert cfg.schedule == "1f1b"


# ---------------------------------------------------------------------- #
# numerics: device == local at M=1; 1F1B == GPipe at M=4
# ---------------------------------------------------------------------- #

def test_m1_device_bit_identical_to_local():
    """At M=1 the device chain and the LocalTransport chain run the
    same programs on the same buffers — the zero-copy relay must add no
    arithmetic: loss series identical bit for bit."""
    local = _losses(1, "gpipe", "local")
    device = _losses(1, "gpipe", "device")
    assert device == local


def test_m4_1f1b_bit_identical_to_gpipe():
    """1F1B changes WHEN microbatches enter the wire, never the math:
    every microbatch still sees the same step-start params snapshot and
    cotangents accumulate in microbatch order, so the loss series is
    bit-identical to GPipe at M=4 (stronger than the M=1 contract)."""
    gpipe = _losses(4, "gpipe", "device")
    onefb = _losses(4, "1f1b", "device")
    assert onefb == gpipe


def test_m4_1f1b_device_matches_local_gpipe():
    """Cross product: the device-native 1F1B chain lands on the exact
    trajectory of the PR-14 LocalTransport GPipe chain."""
    assert _losses(4, "1f1b", "device") == _losses(4, "gpipe", "local")


# ---------------------------------------------------------------------- #
# the zero-copy pin: hop_host_copies == 0, watchdog-clean steady state
# ---------------------------------------------------------------------- #

def test_device_chain_zero_host_copies_and_watchdog_clean():
    """The hop path never materializes host numpy (the explicit
    counter, because the transfer guard is inert on the CPU backend)
    and the dispatch watchdog sees zero unexpected D2H and zero
    steady-state recompiles across warm steps."""
    dispatch_debug.force(True)
    try:
        tr = dispatch_debug.tracker()
        runner, stages, transports = _chain(4, "1f1b", "device")
        try:
            for i in range(2):  # compile steps
                runner.step(*_batch(i), i)
            g0 = tr.gauges()
            for i in range(2, 5):  # steady state
                runner.step(*_batch(i), i)
            g1 = tr.gauges()
        finally:
            _close(runner, stages)
        for t in transports:
            assert t.stats.counters.get(spans.HOP_HOST_COPIES, 0) == 0
        assert g1["unexpected_d2h_total"] == g0["unexpected_d2h_total"]
        assert g1["steady_state_recompiles"] == g0["steady_state_recompiles"]
    finally:
        dispatch_debug.force(False)


def test_local_transport_hop_payload_passthrough():
    """Satellite (a): on the default path (through_codec=False,
    compress=None) LocalTransport's hop payloads pass through untouched
    — the very same object, no np.asarray, no codec round-trip."""
    plan = get_plan(model="split_cnn_chain3", mode="split")
    cfg = _cfg(1)
    sample = np.zeros((BATCH, 28, 28, 1), np.float32)
    s1 = StageRuntime(plan, 1, cfg, jax.random.PRNGKey(SEED), sample)
    try:
        t = LocalTransport(s1)
        x = np.ones((2, 2), np.float32)
        assert t._hop_payload(x) is x
        t_codec = LocalTransport(s1, through_codec=True)
        assert t_codec._hop_payload(x) is not x
    finally:
        s1.close()


# ---------------------------------------------------------------------- #
# ppermute parity: the in-mesh collective path computes the same chain
# ---------------------------------------------------------------------- #

def test_ppermute_mesh_parity():
    """With a named pipe mesh (conftest forces 8 host devices) every
    hop rides the make_hop_shift ppermute collective between pipe
    ranks; the loss trajectory must equal the meshless relay's, and the
    hop path still counts zero host copies."""
    from split_learning_tpu.parallel.mesh import make_mesh
    if len(jax.devices()) < 3:
        pytest.skip("needs >= 3 devices for a pipe mesh")
    mesh = make_mesh(1, 3)
    plain = _losses(2, "1f1b", "device", steps=3)
    runner, stages, transports = _chain(2, "1f1b", "device", mesh=mesh)
    try:
        meshed = [runner.step(*_batch(i), i) for i in range(3)]
    finally:
        _close(runner, stages)
    assert meshed == plain
    for t in transports:
        assert t.stats.counters.get(spans.HOP_HOST_COPIES, 0) == 0


def test_make_hop_shift_moves_rank_to_rank():
    from split_learning_tpu.parallel.mesh import make_mesh
    from split_learning_tpu.parallel.pipeline import make_hop_shift
    if len(jax.devices()) < 3:
        pytest.skip("needs >= 3 devices for a pipe mesh")
    mesh = make_mesh(1, 3)
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    shifted = make_hop_shift(mesh, 0, 2)(x)
    np.testing.assert_array_equal(np.asarray(shifted), x)
    with pytest.raises(ValueError):
        make_hop_shift(mesh, 1, 1)
    with pytest.raises(ValueError):
        make_hop_shift(mesh, 0, 7)


# ---------------------------------------------------------------------- #
# surfaces: report/trace schedule fields; DeviceTransport scope errors
# ---------------------------------------------------------------------- #

def test_stage_report_and_trace_carry_schedule():
    runner, stages, _ = _chain(4, "1f1b", "device")
    try:
        runner.step(*_batch(0), 0)
        rows = runner.stage_report()
        for row in rows:
            assert row["schedule"] == "1f1b"
            assert row["warmup_depth"] == 3
            assert row["bubble_theoretical_gpipe"] == pytest.approx(2 / 6)
            assert row["bubble_theoretical_1f1b"] == pytest.approx(2 / 6)
        meta = runner.trace_metadata()
        assert meta["schedule"] == "1f1b"
        assert meta["warmup_depth"] == 3
        assert meta["device_native"] is True
    finally:
        _close(runner, stages)


def test_device_transport_rejects_two_party_ops():
    plan = get_plan(model="split_cnn_chain3", mode="split")
    s1 = StageRuntime(plan, 1, _cfg(1), jax.random.PRNGKey(SEED),
                      np.zeros((BATCH, 28, 28, 1), np.float32))
    try:
        t = DeviceTransport(s1)
        for call in (lambda: t.split_step(None, None, 0),
                     lambda: t.u_forward(None, 0),
                     lambda: t.u_backward(None, 0),
                     lambda: t.aggregate(None, 0, 0.0, 0)):
            with pytest.raises(NotImplementedError):
                call()
    finally:
        s1.close()


# ---------------------------------------------------------------------- #
# SLT115: the invariant fires on depth-bound and ordering violations
# ---------------------------------------------------------------------- #

class _Run:
    def __init__(self, notes):
        self.schedule_id = "t0"
        self.notes = notes


def test_onefb_invariant_fires_on_depth_overflow():
    from split_learning_tpu.analysis.invariants import (
        Violation, onefb_hop_order)
    run = _Run([("inflight", {"depth": 4, "bound": 3})])
    with pytest.raises(Violation, match="exceeds the 1F1B window"):
        onefb_hop_order(run)


def test_onefb_invariant_relays_order_violations():
    from split_learning_tpu.analysis.invariants import (
        Violation, onefb_hop_order)
    run = _Run([
        ("hop_sent", {"stage": 1, "dir": "fwd", "step": 0, "mb": 0}),
        ("hop_sent", {"stage": 1, "dir": "bwd", "step": 0, "mb": 0}),
        ("hop_apply", {"stage": 1, "dir": "bwd", "step": 0, "mb": 0}),
        ("hop_apply", {"stage": 1, "dir": "fwd", "step": 0, "mb": 0}),
    ])
    with pytest.raises(Violation) as exc:
        onefb_hop_order(run)
    assert exc.value.invariant == "onefb_hop_order"


def test_onefb_invariant_registered_as_slt115():
    from split_learning_tpu.analysis.invariants import (
        INVARIANTS, RULE_OF_INVARIANT)
    assert "onefb_hop_order" in INVARIANTS
    assert RULE_OF_INVARIANT["onefb_hop_order"] == "SLT115"
    from split_learning_tpu.analysis.scenarios import SCENARIOS
    assert "onefb_hop_order" in SCENARIOS
    assert "onefb_hop_order" in SCENARIOS["onefb_hop_order"].invariants
