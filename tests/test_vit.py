"""Split ViT (models/vit.py): the attention trunk on image datasets,
under the same plan machinery as every other family."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from split_learning_tpu.models import get_plan
from split_learning_tpu.models.vit import vit_plan


def images(b=8, hw=28, c=1, seed=0):
    rs = np.random.RandomState(seed)
    return rs.randn(b, hw, hw, c).astype(np.float32)


def test_forward_shapes_and_cut_tensor():
    plan = get_plan(model="vit", mode="split")
    x = jnp.asarray(images())
    params = plan.init(jax.random.PRNGKey(0), x)
    # cut tensor: the patch-token stream [B, T=49, d_model] for MNIST
    # 28x28 at patch 4
    cut = plan.stages[0].apply(params[0], x)
    assert cut.shape == (8, 49, 64)
    logits = plan.apply(params, x)
    assert logits.shape == (8, 10)
    # CIFAR-shaped input tiles to T=64 through the same params? No —
    # pos table slices per T, but conv/blocks are shape-polymorphic:
    # a fresh init at 32x32x3 must produce [B, 64, d_model]
    x32 = jnp.asarray(images(hw=32, c=3))
    p32 = plan.init(jax.random.PRNGKey(0), x32)
    assert plan.stages[0].apply(p32[0], x32).shape == (8, 64, 64)


def test_non_tiling_image_rejected():
    plan = vit_plan(patch=4)
    with pytest.raises(ValueError, match="patches"):
        plan.init(jax.random.PRNGKey(0), jnp.zeros((2, 30, 30, 1)))


def test_u_split_owners_and_composition():
    plan = get_plan(model="vit", mode="u_split")
    assert plan.owners == ("client", "server", "client")
    x = jnp.asarray(images(b=4))
    params = plan.init(jax.random.PRNGKey(1), x)
    # composition == stage-by-stage threading (the invariant every
    # trainer relies on)
    h = x
    for stage, p in zip(plan.stages, params):
        h = stage.apply(p, h)
    np.testing.assert_array_equal(np.asarray(h),
                                  np.asarray(plan.apply(params, x)))


@pytest.mark.slow
def test_fused_training_learns():
    from split_learning_tpu.runtime.fused import FusedSplitTrainer
    from split_learning_tpu.utils import Config

    rs = np.random.RandomState(2)
    xb = rs.randn(16, 28, 28, 1).astype(np.float32)
    yb = rs.randint(0, 10, (16,)).astype(np.int64)
    cfg = Config(model="vit", batch_size=16, lr=0.05)
    tr = FusedSplitTrainer(get_plan(model="vit", mode="split"), cfg,
                           jax.random.PRNGKey(0), xb)
    losses = [tr.train_step(xb, yb) for _ in range(8)]
    assert np.mean(losses[-2:]) < losses[0]


@pytest.mark.slow
def test_cli_trains_vit_on_synthetic(tmp_path, capsys):
    from split_learning_tpu.launch.run import main

    rc = main(["train", "--model", "vit", "--dataset", "synthetic",
               "--transport", "fused", "--steps", "4", "--batch-size", "8",
               "--tracking", "noop", "--data-dir", str(tmp_path)])
    assert rc == 0
    assert "[done]" in capsys.readouterr().out


@pytest.mark.slow
def test_seq_parallel_vit_matches_dense(devices):
    """Patch tokens context-shard like text tokens: ring attention over
    a (data, seq) mesh reproduces the dense forward. T=64 (32x32)
    divides seq=4."""
    from jax.sharding import Mesh

    grid = np.asarray(devices[:8]).reshape(2, 4)
    mesh = Mesh(grid, ("data", "seq"))
    x = jnp.asarray(images(b=4, hw=32, c=3))
    dense = vit_plan()
    ring = vit_plan(mesh=mesh, attn="ring")
    params = dense.init(jax.random.PRNGKey(3), x)
    want = dense.apply(params, x)
    got = jax.jit(lambda p, a: ring.apply(p, a))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
