"""Flash attention kernels (ops/flash_attention.py) vs the dense
reference — interpret mode on CPU, compiled on TPU (same code path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from split_learning_tpu.ops.flash_attention import flash_attention
from split_learning_tpu.ops.ring_attention import full_attention


def qkv(b=2, t=40, h=3, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, t, h, d), jnp.float32)
                 for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t", [40, 128, 200])
def test_forward_matches_dense(causal, t):
    """Ragged (40, 200) and exact (128) T against the 128-block grid."""
    q, k, v = qkv(t=t)
    want = full_attention(q, k, v, causal=causal)
    got = jax.jit(lambda a, b, c: flash_attention(
        a, b, c, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_dense(causal):
    q, k, v = qkv(t=72)  # ragged: 72 pads to one 128 block
    w = jax.random.normal(jax.random.PRNGKey(5), q.shape, jnp.float32)

    def loss(fn):
        return lambda a, b, c: jnp.sum(fn(a, b, c) * w)

    want = jax.grad(loss(lambda a, b, c: full_attention(
        a, b, c, causal=causal)), argnums=(0, 1, 2))(q, k, v)
    got = jax.jit(jax.grad(loss(lambda a, b, c: flash_attention(
        a, b, c, causal=causal)), argnums=(0, 1, 2)))(q, k, v)
    for g, wg in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(wg),
                                   atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("t,block", [(640, 128), (2048, 1024)])
def test_multi_block_gradients(t, block):
    """Multi-block grids under the adaptive block picker: T=640 tiles as
    5x128 (ragged T keeps the small edge), T=2048 as 2x1024 (the large
    edge the round-5 on-chip sweep adopted as default). Exercises the
    inner block loops of all three kernels, causal (block-skew)
    masking on."""
    from split_learning_tpu.ops.flash_attention import _pick_block
    assert _pick_block(t) == block
    q, k, v = qkv(t=t, b=1, h=2)
    w = jax.random.normal(jax.random.PRNGKey(6), q.shape, jnp.float32)
    f = lambda a, b, c: jnp.sum(flash_attention(a, b, c, causal=True) * w)
    r = lambda a, b, c: jnp.sum(full_attention(a, b, c, causal=True) * w)
    got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for g, wg in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(wg),
                                   atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_onepass_backward_matches_two_kernel(monkeypatch, causal):
    """The mid-T one-pass backward (grid (bh, k), VMEM-resident dQ) and
    the long-T two-kernel split must produce the same gradients — the
    form is a perf choice, never a numerics choice. T=256 tiles as
    2x128 so the one-pass q loop and the causal start offset are both
    multi-block."""
    from split_learning_tpu.ops.flash_attention import _make_flash
    q, k, v = qkv(t=256, b=1, h=2, d=16)
    w = jax.random.normal(jax.random.PRNGKey(7), q.shape, jnp.float32)

    grads = {}
    for name, flag in (("onepass", "8192"), ("twokernel", "0")):
        monkeypatch.setenv("SLT_FLASH_ONEPASS_T", flag)
        _make_flash.cache_clear()  # onepass is part of the build key
        f = lambda a, b, c: jnp.sum(
            flash_attention(a, b, c, causal=causal) * w)
        grads[name] = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    _make_flash.cache_clear()
    for g1, g2 in zip(grads["onepass"], grads["twokernel"]):
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   atol=1e-5, rtol=1e-5)


def test_onepass_backward_bf16_storage():
    """The on-chip path runs bf16 storage with f32 accumulation; pin the
    same property in interpret mode: bf16 one-pass grads track the f32
    dense reference within bf16 resolution."""
    q, k, v = qkv(t=128, b=1, h=2, d=16)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    w = jax.random.normal(jax.random.PRNGKey(8), q.shape, jnp.float32)

    f = lambda a, b, c: jnp.sum(
        flash_attention(a, b, c, causal=True).astype(jnp.float32) * w)
    r = lambda a, b, c: jnp.sum(full_attention(a, b, c, causal=True) * w)
    got = jax.grad(f, argnums=(0, 1, 2))(qb, kb, vb)
    want = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for g, wg in zip(got, want):
        np.testing.assert_allclose(np.asarray(g, np.float32),
                                   np.asarray(wg), atol=0.04, rtol=0.04)


def test_onepass_selection_rule(monkeypatch):
    """_use_onepass: VMEM-residency-bounded, env-overridable."""
    import importlib
    # ops/__init__ re-exports the flash_attention *function*, which
    # shadows the submodule attribute `import ... as` would resolve
    fa = importlib.import_module(
        "split_learning_tpu.ops.flash_attention")
    _use_onepass = fa._use_onepass

    # pin the v4/v5 VMEM figure so the assertions are host-independent,
    # and pin interpret mode so this tests the *static* rule only — on
    # a TPU host the raised-limit shapes would otherwise consult real
    # preflight compiles (and cache their verdicts process-wide under
    # the monkeypatched limit)
    monkeypatch.setattr(fa, "_vmem_limit_bytes", lambda: 96 * 1024 * 1024)
    monkeypatch.setattr(fa, "use_interpret", lambda: True)
    # bf16 d=128: _onepass_resident_bytes = 4 KiB/row (double-buffered,
    # lane-padded rows) -> 64 MiB budget caps at tp 16384
    assert _use_onepass(4096, 512, 128, jnp.bfloat16)
    assert _use_onepass(8192, 512, 128, jnp.bfloat16)
    assert _use_onepass(16384, 512, 128, jnp.bfloat16)
    assert not _use_onepass(32768, 512, 128, jnp.bfloat16)
    # f32 rows are 5 KiB: cap drops below tp 16384
    assert _use_onepass(8192, 512, 128, jnp.float32)
    assert not _use_onepass(16384, 512, 128, jnp.float32)


def test_onepass_preflight_fallback(monkeypatch):
    """On a compiled-TPU path (use_interpret() False), a shape needing
    the raised scoped-VMEM limit consults the cached preflight compile
    and falls back to the two-kernel split when the device rejects it —
    the round-4 T=4096 hard compile error can never recur as a
    user-path failure."""
    import importlib
    fa = importlib.import_module(
        "split_learning_tpu.ops.flash_attention")
    monkeypatch.setattr(fa, "_vmem_limit_bytes", lambda: 96 * 1024 * 1024)
    monkeypatch.setattr(fa, "use_interpret", lambda: False)

    # T=4096 bf16 d=128 needs ~16.5 MiB resident: past the 12 MiB
    # default-limit-safe line, so the preflight verdict decides
    monkeypatch.setattr(fa, "_onepass_compile_ok",
                        lambda *a: False)
    assert not fa._use_onepass(4096, 512, 128, jnp.bfloat16)
    monkeypatch.setattr(fa, "_onepass_compile_ok",
                        lambda *a: True)
    assert fa._use_onepass(4096, 512, 128, jnp.bfloat16)
    # T=1024 bf16 fits the 16 MiB default (~4.1 MiB resident): one-pass
    # without any probe even where the probe would say no
    monkeypatch.setattr(fa, "_onepass_compile_ok",
                        lambda *a: False)
    assert fa._use_onepass(1024, 512, 128, jnp.bfloat16)
    # env override short-circuits everything, including the probe
    monkeypatch.setenv("SLT_FLASH_ONEPASS_T", "0")
    assert not fa._use_onepass(1024, 512, 128, jnp.bfloat16)


def test_large_block_always_preflights(monkeypatch):
    """Edges past _SPLIT_BLOCK_MAX must consult the compiler even at
    tiny residency: the _DEFAULT_LIMIT_SAFE skip margin was derived
    for <=512 blocks (~1 MiB of block buffers), and a 1024 edge's f32
    score temporaries (4 MiB per pair) void it."""
    import importlib
    fa = importlib.import_module(
        "split_learning_tpu.ops.flash_attention")
    monkeypatch.setattr(fa, "_vmem_limit_bytes", lambda: 96 * 1024 * 1024)
    monkeypatch.setattr(fa, "use_interpret", lambda: False)
    probed = []

    def probe(*a):
        probed.append(a)
        return False

    monkeypatch.setattr(fa, "_onepass_compile_ok", probe)
    # T=1024 bf16 d=128: ~4.1 MiB resident — inside the skip margin,
    # but block=1024 still must preflight (and honor its verdict)
    assert not fa._use_onepass(1024, 1024, 128, jnp.bfloat16)
    assert probed
    # same shape at the derived-for 512 edge: no probe, static yes
    probed.clear()
    assert fa._use_onepass(1024, 512, 128, jnp.bfloat16)
    assert not probed


def test_resolve_block_caps_split_form(monkeypatch):
    """When the two-kernel split carries the gradient, the whole
    program drops to the proven _SPLIT_BLOCK_MAX edge (the blk-1024
    sweep legs all ran the one-pass backward, so 1024 evidence does
    not cover _dq_kernel/_dkv_kernel); an explicit SLT_FLASH_BLOCK
    tuning override is honored verbatim."""
    import importlib
    fa = importlib.import_module(
        "split_learning_tpu.ops.flash_attention")
    # default path, one-pass selected (interpret mode skips the probe):
    # the swept 1024 edge stands
    monkeypatch.setattr(fa, "use_interpret", lambda: True)
    assert fa._resolve_block(2048, 128, jnp.bfloat16) == (1024, True)
    # force the split form: the edge must drop to the proven 512
    monkeypatch.setenv("SLT_FLASH_ONEPASS_T", "0")
    assert fa._resolve_block(2048, 128, jnp.bfloat16) == (512, False)
    # ragged T already below the cap: unchanged
    assert fa._resolve_block(640, 128, jnp.bfloat16) == (128, False)
    # explicit tuning override rides through the cap untouched
    monkeypatch.setenv("SLT_FLASH_BLOCK", "1024")
    assert fa._resolve_block(2048, 128, jnp.bfloat16) == (1024, False)


@pytest.mark.slow
def test_onepass_vmem_limit_reaches_mosaic():
    """The raised scoped-VMEM limit must actually reach the compiler:
    lower the one-pass backward for the TPU platform (jax.export needs
    no TPU device) and assert the Mosaic custom call's backend config
    carries ``scoped_memory_configs`` with the requested byte size —
    the serialization contract verified against jax's tpu_custom_call
    (jax/_src/tpu_custom_call.py, scoped_memory_configs). Round 4's
    on-chip failure showed the 16 MiB *default* enforced; this pins
    the request side of the fix off-chip."""
    import importlib
    fa = importlib.import_module(
        "split_learning_tpu.ops.flash_attention")
    tp, dp, block = 1024, 128, 512
    seq = jax.ShapeDtypeStruct((1, tp, dp), jnp.bfloat16)
    row = jax.ShapeDtypeStruct((1, tp, fa._ROWW), jnp.float32)
    # interpret-mode pallas_call (the CPU default) never emits the
    # custom call; build the compiled form explicitly
    import split_learning_tpu.ops.common as common
    orig = common.use_interpret
    try:
        common.use_interpret = lambda: False
        fa.use_interpret = common.use_interpret
        call = fa._onepass_call(1, tp, tp, dp, block, 1.0, False, False,
                                jnp.bfloat16)
        exp = jax.export.export(jax.jit(call), platforms=["tpu"])(
            seq, seq, seq, seq, row, row)
    finally:
        common.use_interpret = orig
        fa.use_interpret = orig
    txt = exp.mlir_module()
    assert "scoped_memory_configs" in txt
    assert str(fa._vmem_limit_bytes()) in txt


def test_auto_attention_selection(monkeypatch):
    """attn='auto' resolves per shape by two rules: flash at/past the
    measured round-4 speed crossover (_FLASH_SPEED_T, regardless of
    HBM headroom), and flash wherever dense's quadratic backward
    buffers threaten HBM; dense otherwise. SLT_FLASH_AUTO_T re-pins
    both."""
    from split_learning_tpu.ops.flash_attention import select_attention

    hbm = 16 * 1024 ** 3
    # the measured facts (bench_tpu_transformer_2026-08-01 +
    # tpu_window_runs.jsonl): flash wins on compiled-Mosaic speed at
    # every both-sides-measured T >= 1024, so on the chip
    # (interpret=False) the pin sits at 1024 even when dense fits
    tpu = dict(hbm_bytes=hbm, interpret=False)
    assert select_attention(16, 1024, 2, 2, **tpu) == "flash"
    assert select_attention(16, 4096, 2, 2, **tpu) == "flash"
    assert select_attention(16, 16384, 2, 2, **tpu) == "flash"
    assert select_attention(1, 8192, 1, 2, hbm_bytes=100 * hbm,
                            interpret=False) == "flash"
    # below the speed crossover with huge HBM: dense (T=256 measured
    # dense-ahead, 353 vs 204)
    assert select_attention(1, 512, 1, 2, hbm_bytes=100 * hbm,
                            interpret=False) == "full"
    # the speed rule is compiled-Mosaic-only: on interpreter backends
    # (this CPU test process resolves interpret=True by default) auto
    # keeps XLA dense at speed-rule shapes...
    assert select_attention(16, 4096, 2, 2, hbm_bytes=hbm) == "full"
    assert select_attention(16, 4096, 2, 2, hbm_bytes=hbm,
                            interpret=True) == "full"
    # ...while the HBM rule stays universal — dense's quadratic
    # backward buffers threatening memory force flash on any backend
    assert select_attention(512, 512, 8, 4, hbm_bytes=hbm,
                            interpret=True) == "flash"
    assert select_attention(16, 16384, 2, 2, hbm_bytes=hbm,
                            interpret=True) == "flash"
    # the operator env re-pin is absolute on every backend
    monkeypatch.setenv("SLT_FLASH_AUTO_T", "2048")
    assert select_attention(16, 2048, 2, 2, hbm_bytes=hbm) == "flash"
    assert select_attention(16, 1024, 2, 2, hbm_bytes=hbm,
                            interpret=False) == "full"


@pytest.mark.slow
def test_transformer_auto_matches_dense_at_small_t():
    """attn='auto' at T=32 resolves to dense: the trainer's loss series
    is bit-identical to attn='full'."""
    from split_learning_tpu.models.transformer import transformer_plan
    from split_learning_tpu.runtime.fused import FusedSplitTrainer
    from split_learning_tpu.utils import Config

    rs = np.random.RandomState(1)
    xs = rs.randint(0, 256, (2, 8, 32)).astype(np.int32)
    ys = rs.randint(0, 10, (2, 8)).astype(np.int32)
    cfg = Config(mode="split", model="transformer", batch_size=8,
                 attn="auto")
    dense = FusedSplitTrainer(transformer_plan(), cfg,
                              jax.random.PRNGKey(0), xs[0])
    auto = FusedSplitTrainer(transformer_plan(attn="auto"), cfg,
                             jax.random.PRNGKey(0), xs[0])
    for i in range(2):
        assert auto.train_step(xs[i], ys[i]) == dense.train_step(xs[i], ys[i])


@pytest.mark.slow
def test_transformer_trains_with_flash_attn():
    """attn='flash' is a drop-in for the model family: same init, loss
    matches the dense-attention trainer step for step."""
    from split_learning_tpu.models.transformer import transformer_plan
    from split_learning_tpu.runtime.fused import FusedSplitTrainer
    from split_learning_tpu.utils import Config

    rs = np.random.RandomState(0)
    xs = rs.randint(0, 256, (3, 8, 32)).astype(np.int32)
    ys = rs.randint(0, 10, (3, 8)).astype(np.int32)
    cfg = Config(mode="split", model="transformer", batch_size=8,
                 attn="flash")
    dense = FusedSplitTrainer(transformer_plan(), cfg,
                              jax.random.PRNGKey(0), xs[0])
    flash = FusedSplitTrainer(transformer_plan(attn="flash"), cfg,
                              jax.random.PRNGKey(0), xs[0])
    for i in range(3):
        ld = dense.train_step(xs[i], ys[i])
        lf = flash.train_step(xs[i], ys[i])
        np.testing.assert_allclose(lf, ld, atol=5e-5, rtol=5e-5)


def test_with_lse_strict_requires_causal():
    """strict refines the causal mask; without causal it must be a loud
    error, never silently-unmasked attention."""
    from split_learning_tpu.ops.flash_attention import (
        flash_attention_with_lse)

    q, k, v = qkv(t=8)
    with pytest.raises(ValueError, match="causal"):
        flash_attention_with_lse(q, k, v, causal=False, strict=True)
