"""Flash attention kernels (ops/flash_attention.py) vs the dense
reference — interpret mode on CPU, compiled on TPU (same code path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from split_learning_tpu.ops.flash_attention import flash_attention
from split_learning_tpu.ops.ring_attention import full_attention


def qkv(b=2, t=40, h=3, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, t, h, d), jnp.float32)
                 for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t", [40, 128, 200])
def test_forward_matches_dense(causal, t):
    """Ragged (40, 200) and exact (128) T against the 128-block grid."""
    q, k, v = qkv(t=t)
    want = full_attention(q, k, v, causal=causal)
    got = jax.jit(lambda a, b, c: flash_attention(
        a, b, c, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_dense(causal):
    q, k, v = qkv(t=72)  # ragged: 72 pads to one 128 block
    w = jax.random.normal(jax.random.PRNGKey(5), q.shape, jnp.float32)

    def loss(fn):
        return lambda a, b, c: jnp.sum(fn(a, b, c) * w)

    want = jax.grad(loss(lambda a, b, c: full_attention(
        a, b, c, causal=causal)), argnums=(0, 1, 2))(q, k, v)
    got = jax.jit(jax.grad(loss(lambda a, b, c: flash_attention(
        a, b, c, causal=causal)), argnums=(0, 1, 2)))(q, k, v)
    for g, wg in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(wg),
                                   atol=5e-5, rtol=5e-5)


def test_multi_block_gradients():
    """T=256 = two 128-blocks on both grids: exercises the inner
    block loops of all three kernels, causal (block-skew) masking on."""
    q, k, v = qkv(t=256, b=1, h=2)
    w = jax.random.normal(jax.random.PRNGKey(6), q.shape, jnp.float32)
    f = lambda a, b, c: jnp.sum(flash_attention(a, b, c, causal=True) * w)
    r = lambda a, b, c: jnp.sum(full_attention(a, b, c, causal=True) * w)
    got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for g, wg in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(wg),
                                   atol=1e-4, rtol=1e-4)


def test_transformer_trains_with_flash_attn():
    """attn='flash' is a drop-in for the model family: same init, loss
    matches the dense-attention trainer step for step."""
    from split_learning_tpu.models.transformer import transformer_plan
    from split_learning_tpu.runtime.fused import FusedSplitTrainer
    from split_learning_tpu.utils import Config

    rs = np.random.RandomState(0)
    xs = rs.randint(0, 256, (3, 8, 32)).astype(np.int32)
    ys = rs.randint(0, 10, (3, 8)).astype(np.int32)
    cfg = Config(mode="split", model="transformer", batch_size=8,
                 attn="flash")
    dense = FusedSplitTrainer(transformer_plan(), cfg,
                              jax.random.PRNGKey(0), xs[0])
    flash = FusedSplitTrainer(transformer_plan(attn="flash"), cfg,
                              jax.random.PRNGKey(0), xs[0])
    for i in range(3):
        ld = dense.train_step(xs[i], ys[i])
        lf = flash.train_step(xs[i], ys[i])
        np.testing.assert_allclose(lf, ld, atol=5e-5, rtol=5e-5)
