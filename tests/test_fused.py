"""Fused SPMD trainer: must equal the MPMD transport path numerically, scale
over the data axis, and keep microbatch accumulation equivalent
(SURVEY.md §4 item 4: mesh tests on the 8-device virtual CPU backend)."""

import jax
import jax.numpy as jnp
import numpy as np

from split_learning_tpu.models import get_plan
from split_learning_tpu.parallel import make_mesh
from split_learning_tpu.runtime import ServerRuntime, SplitClientTrainer
from split_learning_tpu.runtime.fused import FusedSplitTrainer
from split_learning_tpu.transport import LocalTransport
from split_learning_tpu.utils import Config
import pytest

SEED = 3
BATCH = 32
N_STEPS = 6


def batches():
    rs = np.random.RandomState(9)
    return [(rs.randn(BATCH, 28, 28, 1).astype(np.float32),
             rs.randint(0, 10, (BATCH,)).astype(np.int64))
            for _ in range(N_STEPS)]


def test_fused_equals_transport_path():
    """The in-XLA cut-layer exchange and the explicit transport exchange
    are the same computation."""
    cfg = Config(mode="split", batch_size=BATCH)
    plan = get_plan(mode="split")
    data = batches()

    fused = FusedSplitTrainer(plan, cfg, jax.random.PRNGKey(SEED), data[0][0])
    fused_losses = [fused.train_step(x, y) for x, y in data]

    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(SEED), data[0][0])
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(SEED),
                                LocalTransport(server))
    mpmd_losses = [client.train_step(x, y, i) for i, (x, y) in enumerate(data)]

    np.testing.assert_allclose(fused_losses, mpmd_losses, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_train_epoch_scan_matches_stepwise():
    """T steps under one lax.scan dispatch == T individual train_step
    dispatches (the jit-once/scan-many throughput path)."""
    cfg = Config(mode="split", batch_size=BATCH)
    plan = get_plan(mode="split")
    data = batches()

    stepwise = FusedSplitTrainer(plan, cfg, jax.random.PRNGKey(SEED),
                                 data[0][0])
    step_losses = [stepwise.train_step(x, y) for x, y in data]

    scanned = FusedSplitTrainer(plan, cfg, jax.random.PRNGKey(SEED),
                                data[0][0])
    xs = np.stack([x for x, _ in data])
    ys = np.stack([y for _, y in data])
    scan_losses = np.asarray(scanned.train_epoch(xs, ys))

    np.testing.assert_allclose(step_losses, scan_losses, rtol=1e-5,
                               atol=1e-6)
    # scan-compiled vs step-compiled programs fuse in different orders;
    # params agree to float noise, not bit-exactly
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5),
        jax.device_get(stepwise.state.params),
        jax.device_get(scanned.state.params))


@pytest.mark.slow
def test_train_epoch_scan_on_dp_mesh(devices):
    """Scanned steps with the batch axis sharded over 4 clients."""
    cfg = Config(mode="split", batch_size=BATCH, num_clients=4)
    plan = get_plan(mode="split")
    data = batches()
    mesh = make_mesh(num_clients=4, num_stages=1, devices=devices[:4])
    dp = FusedSplitTrainer(plan, cfg, jax.random.PRNGKey(SEED), data[0][0],
                           mesh=mesh)
    xs = np.stack([x for x, _ in data])
    ys = np.stack([y for _, y in data])
    losses = np.asarray(dp.train_epoch(xs, ys))
    single = FusedSplitTrainer(plan, cfg, jax.random.PRNGKey(SEED),
                               data[0][0])
    ref = [single.train_step(x, y) for x, y in data]
    np.testing.assert_allclose(losses, ref, rtol=1e-4, atol=1e-5)


def test_fused_dp_mesh_matches_single_device(devices):
    """Config 3: batch sharded over 4 data-parallel clients with psum
    gradient aggregation must equal single-device training."""
    cfg = Config(mode="split", batch_size=BATCH, num_clients=4)
    plan = get_plan(mode="split")
    data = batches()

    mesh = make_mesh(num_clients=4, num_stages=1, devices=devices[:4])
    dp = FusedSplitTrainer(plan, cfg, jax.random.PRNGKey(SEED), data[0][0],
                           mesh=mesh)
    dp_losses = [dp.train_step(x, y) for x, y in data]

    single = FusedSplitTrainer(plan, cfg, jax.random.PRNGKey(SEED), data[0][0])
    single_losses = [single.train_step(x, y) for x, y in data]

    np.testing.assert_allclose(dp_losses, single_losses, rtol=1e-4, atol=1e-5)
    # params stay replicated and identical to the single-device run
    for a, b in zip(jax.tree_util.tree_leaves(dp.params),
                    jax.tree_util.tree_leaves(single.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_microbatched_matches_full_batch():
    """Config 4 groundwork: scan-accumulated microbatch gradients equal the
    full-batch gradient (mean-of-means with equal microbatch sizes)."""
    plan = get_plan(mode="split")
    data = batches()
    full = FusedSplitTrainer(plan, Config(mode="split", batch_size=BATCH),
                             jax.random.PRNGKey(SEED), data[0][0])
    micro = FusedSplitTrainer(
        plan, Config(mode="split", batch_size=BATCH, microbatches=4),
        jax.random.PRNGKey(SEED), data[0][0])
    f_losses = [full.train_step(x, y) for x, y in data]
    m_losses = [micro.train_step(x, y) for x, y in data]
    np.testing.assert_allclose(f_losses, m_losses, rtol=1e-5, atol=1e-6)
