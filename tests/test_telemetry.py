"""Fleet telemetry plane (PR 17): obs/telemetry.py windowed rings +
SLO burn-rate tracking, obs/federate.py cross-party federation and
critical-path attribution, and the exposition/zero-overhead edges they
lean on.

Pins, in order: ring window math is exact under an injectable virtual
clock (deltas, rates, empty idle windows, forced partial windows,
capacity); counter and histogram resets fall back to the post-restart
cumulative value (the Prometheus ``rate()`` convention);
``histogram_percentile`` returns 0.0 — never NaN — on empty deltas and
clamps +Inf-slot percentiles to the last finite edge; the multi-window
burn-rate pair fires and clears deterministically on synthetic window
streams and journals typed FL_SLO_ALERT records; federation merges
three synthetic parties into one keyed view with fleet/tenant rate
splits; critical-path attribution decomposes a recorded 3-stage
fixture into compute/queue/wire/bubble and names the slow party;
labeled series render with correct Prometheus label escaping; the
``/telemetry`` endpoint serves ring dumps (404 when off); and with
telemetry fully off the chain's loss series is bit-for-bit identical
to a telemetry-on twin — the plane never touches arithmetic."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from split_learning_tpu.obs import flight
from split_learning_tpu.obs import spans
from split_learning_tpu.obs import telemetry as obs_telemetry
from split_learning_tpu.obs import trace as obs_trace
from split_learning_tpu.obs.federate import (
    FleetCollector, bottleneck_histogram, critical_path, merge_fleet,
    party_key, serve_telemetry, split_tenant)
from split_learning_tpu.obs.metrics import (
    Histogram, Registry, escape_label_value, histogram_delta,
    histogram_percentile, render_prometheus)
from split_learning_tpu.obs.telemetry import (
    SLOTracker, SloObjective, TelemetryRing)


class VClock:
    """Injectable monotonic clock (SLT004-clean window math)."""

    def __init__(self, t: float = 0.0) -> None:
        self.t = float(t)

    def __call__(self) -> float:
        return self.t


def _ring_over(state, **kw):
    """Ring over a mutable metrics()-shaped dict (snapshot_fn reads the
    live dict, the ring's delta logic does the rest)."""
    clk = VClock(0.0)
    ring = TelemetryRing(
        lambda: {"counters": dict(state.get("counters", {})),
                 "histograms": {k: dict(v) for k, v in
                                state.get("histograms", {}).items()},
                 "gauges": dict(state.get("gauges", {}))},
        party="test", clock=clk, **kw)
    return ring, clk


def _hist_snap(*values):
    h = Histogram()
    for v in values:
        h.observe(v)
    return h.snapshot()


# ---------------------------------------------------------------------- #
# ring window math under the virtual clock
# ---------------------------------------------------------------------- #

def test_ring_windows_deltas_and_rates():
    state = {"counters": {"steps_total": 0.0}, "histograms": {},
             "gauges": {"depth": 0.0}}
    ring, clk = _ring_over(state, interval_s=1.0, capacity=10)

    state["counters"]["steps_total"] = 5.0
    state["gauges"]["depth"] = 3.0
    clk.t = 1.0
    assert ring.advance() == 1
    (w,) = ring.windows()
    assert w["index"] == 0
    assert w["t_start"] == 0.0 and w["t_end"] == 1.0
    assert w["counters"]["steps_total"] == 5.0
    assert w["rates"]["steps_total"] == 5.0
    assert w["gauges"]["depth"] == 3.0

    # second window: only the delta (2 more steps -> rate 2/s)
    state["counters"]["steps_total"] = 7.0
    clk.t = 2.0
    assert ring.advance() == 1
    assert ring.windows()[-1]["counters"]["steps_total"] == 2.0
    assert ring.windows()[-1]["rates"]["steps_total"] == 2.0


def test_ring_same_interval_advance_is_noop():
    ring, clk = _ring_over({"counters": {"c": 1.0}}, interval_s=1.0)
    clk.t = 0.5
    assert ring.advance() == 0           # window 0 still open
    assert ring.windows() == []
    clk.t = 1.0
    assert ring.advance() == 1
    assert ring.advance() == 0           # idempotent at the boundary


def test_ring_skipped_intervals_emit_empty_windows():
    """A scrape gap attributes the whole delta to the latest complete
    window; the skipped intervals stay in the ring as explicitly empty
    windows so the time axis stays uniform (burn windows depend on
    it)."""
    state = {"counters": {"c": 0.0}}
    ring, clk = _ring_over(state, interval_s=1.0, capacity=10)
    state["counters"]["c"] = 9.0
    clk.t = 4.2                          # windows 0..3 complete
    assert ring.advance() == 4
    ws = ring.windows()
    assert [w["index"] for w in ws] == [0, 1, 2, 3]
    assert all(w["counters"] == {} for w in ws[:3])
    assert ws[3]["counters"]["c"] == 9.0


def test_ring_force_closes_partial_window_with_honest_width():
    state = {"counters": {"c": 0.0}}
    ring, clk = _ring_over(state, interval_s=1.0)
    state["counters"]["c"] = 4.0
    clk.t = 0.5
    assert ring.advance(force=True) == 1
    w = ring.windows()[-1]
    assert w["t_end"] == pytest.approx(0.5)
    assert w["rates"]["c"] == pytest.approx(8.0)   # 4 events / 0.5 s
    # a second force inside the same interval cannot invert the axis
    clk.t = 0.6
    ring.advance(force=True)
    w2 = ring.windows()[-1]
    assert w2["t_end"] >= w2["t_start"]


def test_ring_capacity_bounds_the_window_list():
    state = {"counters": {"c": 0.0}}
    ring, clk = _ring_over(state, interval_s=1.0, capacity=3)
    for i in range(1, 8):
        clk.t = float(i)
        ring.advance()
    ws = ring.windows()
    assert len(ws) == 3
    assert [w["index"] for w in ws] == [4, 5, 6]
    assert ring.windows(last=2)[0]["index"] == 5


def test_ring_counter_reset_falls_back_to_post_restart_value():
    state = {"counters": {"c": 10.0}}
    ring, clk = _ring_over(state, interval_s=1.0)
    clk.t = 1.0
    ring.advance()
    state["counters"]["c"] = 4.0         # party restarted mid-scrape
    clk.t = 2.0
    ring.advance()
    assert ring.windows()[-1]["counters"]["c"] == 4.0


def test_ring_histogram_windows_roll_percentiles():
    state = {"histograms": {spans.DISPATCH: _hist_snap(0.004)}}
    ring, clk = _ring_over(state, interval_s=1.0)
    clk.t = 1.0
    ring.advance()
    p = ring.windows()[-1]["percentiles"][spans.DISPATCH]
    assert 2.5 <= p["p99"] <= 5.0        # ms, within the 4 ms bucket
    # next window: one much slower observation dominates the DELTA
    # percentiles even though the cumulative histogram is mostly fast
    state["histograms"][spans.DISPATCH] = _hist_snap(0.004, 0.9)
    clk.t = 2.0
    ring.advance()
    w = ring.windows()[-1]
    assert w["histograms"][spans.DISPATCH]["count"] == 1
    assert w["percentiles"][spans.DISPATCH]["p99"] >= 500.0
    # idle window: no delta -> no percentile entry (not NaN, not 0 spam)
    clk.t = 3.0
    ring.advance()
    assert spans.DISPATCH not in ring.windows()[-1]["percentiles"]


def test_ring_dump_schema():
    ring, clk = _ring_over({"counters": {"c": 1.0}}, interval_s=1.0)
    clk.t = 1.0
    ring.advance()
    d = ring.dump()
    assert d["version"] == 1 and d["kind"] == "slt-telemetry"
    assert d["party"] == "test"
    assert d["interval_s"] == 1.0
    assert d["slo"] is None
    assert len(d["windows"]) == 1
    json.dumps(d)                        # JSON-safe by construction


# ---------------------------------------------------------------------- #
# histogram delta / percentile edges (satellite b)
# ---------------------------------------------------------------------- #

def test_histogram_percentile_empty_delta_is_zero_not_nan():
    assert histogram_percentile({}, 99.0) == 0.0
    assert histogram_percentile({"count": 0}, 50.0) == 0.0
    empty = histogram_delta(_hist_snap(0.01), _hist_snap(0.01))
    assert empty["count"] == 0
    assert histogram_percentile(empty, 99.0) == 0.0


def test_histogram_percentile_inf_slot_clamps_to_last_finite_edge():
    snap = _hist_snap(50.0, 60.0, 70.0)  # all beyond the 10 s top edge
    assert histogram_percentile(snap, 50.0) == snap["buckets"][-1]
    assert histogram_percentile(snap, 99.0) == snap["buckets"][-1]


def test_histogram_percentile_rejects_bad_quantile():
    with pytest.raises(ValueError):
        histogram_percentile(_hist_snap(0.01), 101.0)
    with pytest.raises(ValueError):
        histogram_percentile(_hist_snap(0.01), -1.0)


def test_histogram_delta_subtracts_and_tolerates_reset():
    prev = _hist_snap(0.004)
    cur = _hist_snap(0.004, 0.9)
    d = histogram_delta(cur, prev)
    assert d["count"] == 1
    assert d["sum"] == pytest.approx(0.9)
    assert sum(d["cumulative"][-1:]) == 2 - 1
    # reset: cur strictly smaller than prev -> delta is cur itself
    r = histogram_delta(prev, cur)
    assert r["count"] == prev["count"]
    assert r["cumulative"] == prev["cumulative"]


# ---------------------------------------------------------------------- #
# SLO burn-rate pair (deterministic fire / clear)
# ---------------------------------------------------------------------- #

def _lat_window(idx, slow, fast):
    """A ring window whose dispatch delta has ``slow`` observations over
    100 ms and ``fast`` under 1 ms."""
    h = Histogram()
    for _ in range(slow):
        h.observe(0.9)
    for _ in range(fast):
        h.observe(0.0005)
    return {"index": idx, "counters": {}, "gauges": {},
            "histograms": {spans.DISPATCH: h.snapshot()},
            "percentiles": {}}


def test_burn_rate_pair_fires_and_clears_deterministically():
    obj = SloObjective(kind="latency", tenant=0, target=0.99,
                       slo_ms=100.0)
    tr = SLOTracker([obj], fast_windows=2, slow_windows=4,
                    threshold=1.0)
    # two all-bad windows: burn 100x on both horizons -> fires once
    fired = tr.observe_window(_lat_window(0, slow=4, fast=0))
    assert [a.state for a in fired] == ["firing"]
    assert tr.observe_window(_lat_window(1, slow=4, fast=0)) == []
    assert tr.firing() == [{"tenant": 0, "objective": "latency"}]
    g = tr.burn_gauges()
    assert g[f"{spans.SLO_BURN_FAST}_latency_t0"] > 1.0
    assert g[f"{spans.SLO_BURN_SLOW}_latency_t0"] > 1.0
    # idle windows are skipped, not counted as good: still firing
    assert tr.observe_window(_lat_window(2, slow=0, fast=0)) == []
    assert tr.firing() != []
    # four clean windows push both horizons under threshold -> clears
    cleared = []
    for i in range(3, 7):
        cleared += tr.observe_window(_lat_window(i, slow=0, fast=50))
    assert [a.state for a in cleared] == ["cleared"]
    assert tr.firing() == []
    states = [a["state"] for a in tr.alerts()]
    assert states == ["firing", "cleared"]


def test_burn_rate_single_bad_window_does_not_page():
    """The slow horizon rejects blips: one bad window in a long good
    stream keeps burn_slow under threshold -> never fires."""
    obj = SloObjective(kind="latency", target=0.9, slo_ms=100.0)
    tr = SLOTracker([obj], fast_windows=1, slow_windows=8,
                    threshold=1.5)
    for i in range(6):
        assert tr.observe_window(_lat_window(i, slow=0, fast=20)) == []
    assert tr.observe_window(_lat_window(6, slow=1, fast=19)) == []
    assert tr.alerts() == []


def test_availability_objective_uses_tenant_counters():
    obj = SloObjective(kind="availability", tenant=1, target=0.5)
    w = {"index": 0, "histograms": {},
         "counters": {f"{spans.ADMISSION_ADMITTED}_t1": 1.0,
                      f"{spans.ADMISSION_REJECTED}_t1": 3.0}}
    assert obj.window_error_rate(w) == pytest.approx(0.75)
    assert obj.window_error_rate(
        {"index": 1, "histograms": {}, "counters": {}}) is None


def test_slo_alert_journaled_to_flight_recorder():
    fl = flight.enable(party="proc")
    try:
        tr = SLOTracker([SloObjective(kind="latency", slo_ms=100.0)],
                        fast_windows=1, slow_windows=2)
        tr.observe_window(_lat_window(0, slow=3, fast=0))
        evs = [e for e in fl.events() if e["name"] == spans.FL_SLO_ALERT]
        assert len(evs) == 1
        assert evs[0]["fields"]["state"] == "firing"
        assert evs[0]["fields"]["objective"] == "latency"
    finally:
        flight.disable()


def test_ring_merges_burn_gauges_into_windows():
    tr = SLOTracker([SloObjective(kind="latency", slo_ms=100.0)],
                    fast_windows=1, slow_windows=2)
    state = {"histograms": {spans.DISPATCH: _hist_snap(0.9, 0.9)}}
    ring, clk = _ring_over(state, interval_s=1.0, slo=tr)
    clk.t = 1.0
    ring.advance()
    w = ring.windows()[-1]
    assert w["gauges"][f"{spans.SLO_BURN_FAST}_latency_t0"] > 1.0
    d = ring.dump()
    assert d["slo"]["firing"] == [{"tenant": 0, "objective": "latency"}]
    assert [a["state"] for a in d["slo"]["alerts"]] == ["firing"]


# ---------------------------------------------------------------------- #
# federation: merge + critical path on a synthetic 3-party fixture
# ---------------------------------------------------------------------- #

def _dump(party, windows, slo=None):
    return {"version": 1, "kind": "slt-telemetry", "party": party,
            "interval_s": 1.0, "capacity": 10,
            "next_index": len(windows), "windows": windows,
            "slo": slo}


def _win(idx, hists=None, counters=None, rates=None):
    return {"index": idx, "t_start": float(idx),
            "t_end": float(idx + 1), "interval_s": 1.0,
            "counters": counters or {}, "rates": rates or {},
            "gauges": {}, "histograms": hists or {}, "percentiles": {}}


def _sum_hist(total_s, count=1):
    """A window-delta histogram whose sum/count are what the critical
    path reads (bucket detail irrelevant to attribution sums)."""
    return {"buckets": (10.0,), "cumulative": [count],
            "sum": float(total_s), "count": int(count)}


def test_party_key_and_tenant_split():
    assert party_key("hub") == "hub"
    assert party_key("stage", 2) == "stage2"
    assert party_key("server", None, 1) == "server.r1"
    assert split_tenant("admission_admitted_t2") == (
        "admission_admitted", 2)
    assert split_tenant("steps_total") == ("steps_total", None)


def test_merge_fleet_three_parties():
    scraped = [
        {"role": "hub", "stage": None, "replica": None, "key": "hub",
         "error": None, "telemetry": _dump("hub", [
             _win(0, rates={"hub_steps_total": 2.0})])},
        {"role": "stage", "stage": 1, "replica": None, "key": "stage1",
         "error": None, "telemetry": _dump("stage1", [
             _win(0, rates={"hop_fwd_total": 8.0,
                            "admission_admitted_t0": 3.0})])},
        {"role": "stage", "stage": 2, "replica": None, "key": "stage2",
         "error": None, "telemetry": _dump("stage2", [
             _win(0, rates={"hop_fwd_total": 8.0,
                            "admission_admitted_t0": 5.0})],
             slo={"burn": {"slo_burn_rate_fast_latency_t0": 2.5},
                  "firing": [{"tenant": 0, "objective": "latency"}],
                  "alerts": []})},
    ]
    view = merge_fleet(scraped)
    assert set(view["parties"]) == {"hub", "stage1", "stage2"}
    assert view["fleet_rates"]["hop_fwd_total"] == pytest.approx(16.0)
    assert view["tenant_rates"]["t0"]["admission_admitted"] == (
        pytest.approx(8.0))
    assert view["slo_burn"][
        "stage2:slo_burn_rate_fast_latency_t0"] == 2.5
    assert view["slo_firing"] == [
        {"party": "stage2", "tenant": 0, "objective": "latency"}]


def _fixture_scrape(stage1_compute, stage2_compute, hub_wire,
                    step_s=1.0, queue1=0.05):
    hub_h = {spans.STEP_TOTAL: _sum_hist(step_s, 2),
             spans.WIRE: _sum_hist(hub_wire, 6)}
    s1_h = {spans.DISPATCH: _sum_hist(stage1_compute, 4),
            spans.QUEUE_WAIT: _sum_hist(queue1, 4)}
    s2_h = {spans.DISPATCH: _sum_hist(stage2_compute, 4)}
    return [
        {"role": "hub", "stage": None, "replica": None, "key": "hub",
         "error": None, "telemetry": _dump("hub", [_win(0, hub_h)])},
        {"role": "stage", "stage": 1, "replica": None, "key": "stage1",
         "error": None,
         "telemetry": _dump("stage1", [_win(0, s1_h)])},
        {"role": "stage", "stage": 2, "replica": None, "key": "stage2",
         "error": None,
         "telemetry": _dump("stage2", [_win(0, s2_h)])},
    ]


def test_critical_path_decomposition_names_slow_stage():
    cp = critical_path(_fixture_scrape(
        stage1_compute=0.2, stage2_compute=0.6, hub_wire=0.5))
    assert len(cp) == 1
    w = cp[0]
    assert w["steps"] == 2
    assert w["compute_s"]["stage2"] == pytest.approx(0.6)
    assert w["queue_s"]["stage1"] == pytest.approx(0.05)
    # wire brackets remote work: 0.5 - (0.2+0.6+0.05) clamps to 0
    assert w["wire_s"] == 0.0
    assert w["bubble_s"] == pytest.approx(1.0 - 0.85)
    assert w["bottleneck"]["party"] == "stage2"
    assert w["bottleneck"]["kind"] == "compute"
    assert w["bottleneck"]["share"] == pytest.approx(0.6)


def test_critical_path_wire_bottleneck_and_histogram():
    cp = critical_path(_fixture_scrape(
        stage1_compute=0.05, stage2_compute=0.05, hub_wire=0.9,
        queue1=0.0))
    assert cp[0]["bottleneck"]["party"] == "hub"
    assert cp[0]["bottleneck"]["kind"] == "wire"
    assert cp[0]["wire_s"] == pytest.approx(0.8)
    assert bottleneck_histogram(cp) == {"hub": 1}


def test_critical_path_skips_idle_and_needs_a_hub():
    scraped = _fixture_scrape(0.1, 0.1, 0.1)
    scraped[0]["telemetry"]["windows"][0]["histograms"] = {}
    assert critical_path(scraped) == []          # no hub steps
    assert critical_path(scraped[1:]) == []      # no hub party


def test_collector_dead_party_is_data_not_a_crash():
    view = FleetCollector([
        {"role": "hub", "dump": _dump("hub", [])},
        {"role": "stage", "stage": 1,
         "url": "http://127.0.0.1:1/nope"},   # nothing listens there
    ], timeout_s=0.2).collect()
    assert view["parties"]["stage1"]["error"]
    assert view["parties"]["hub"]["error"] is None
    assert view["critical_path"] == []


def test_collector_in_process_ring_source():
    state = {"counters": {"hub_steps_total": 0.0}}
    ring, clk = _ring_over(state, interval_s=1.0)
    state["counters"]["hub_steps_total"] = 3.0
    clk.t = 1.0
    view = FleetCollector(
        [{"role": "hub", "ring": ring}]).collect()
    assert view["parties"]["hub"]["windows"] == 1
    assert view["parties"]["hub"]["rates"]["hub_steps_total"] == 3.0


# ---------------------------------------------------------------------- #
# exposition: label escaping + labeled series (satellite c)
# ---------------------------------------------------------------------- #

def test_escape_label_value():
    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value("a\\b") == "a\\\\b"
    assert escape_label_value("a\nb") == "a\\nb"
    # backslash first: an embedded \" round-trips unambiguously
    assert escape_label_value('\\"') == '\\\\\\"'


def test_render_prometheus_labeled_series():
    snap = {"histograms": {}, "counters": {"hop_fwd": 7.0},
            "gauges": {}, "phase_fractions": {},
            "labeled": [
                {"name": "hop_fwd", "type": "counter",
                 "labels": {"replica": "0"}, "value": 3.0},
                {"name": "hop_fwd", "type": "counter",
                 "labels": {"replica": "1"}, "value": 4.0},
                {"name": "weird", "type": "gauge",
                 "labels": {"path": 'a"b\nc'}, "value": 1.0},
            ]}
    text = render_prometheus(snap)
    assert 'slt_hop_fwd{replica="0"} 3' in text
    assert 'slt_hop_fwd{replica="1"} 4' in text
    assert 'slt_weird{path="a\\"b\\nc"} 1' in text
    # one TYPE header per metric even when labeled series share the
    # name with the un-labeled aggregate
    assert text.count("# TYPE slt_hop_fwd counter") == 1
    assert "# TYPE slt_weird gauge" in text


# ---------------------------------------------------------------------- #
# env knobs + endpoint + zero-overhead-off bit identity
# ---------------------------------------------------------------------- #

def test_env_config_parses_knobs(monkeypatch):
    monkeypatch.delenv("SLT_TELEMETRY", raising=False)
    assert obs_telemetry.env_config() is None
    monkeypatch.setenv("SLT_TELEMETRY", "0")
    assert obs_telemetry.env_config() is None
    monkeypatch.setenv("SLT_TELEMETRY", "1")
    monkeypatch.setenv("SLT_TELEMETRY_INTERVAL_S", "0.5")
    monkeypatch.setenv("SLT_TELEMETRY_CAPACITY", "7")
    cfg = obs_telemetry.env_config()
    assert cfg == {"interval_s": 0.5, "capacity": 7}
    assert obs_telemetry.tracker_from_config(cfg) is None
    monkeypatch.setenv("SLT_TELEMETRY_SLO_MS", "25")
    monkeypatch.setenv("SLT_TELEMETRY_BURN_THRESHOLD", "2.0")
    cfg = obs_telemetry.env_config()
    tr = obs_telemetry.tracker_from_config(cfg, tenants=2)
    assert tr.threshold == 2.0
    kinds = [(o.kind, o.tenant) for o in tr.objectives]
    assert ("latency", 0) in kinds and ("availability", 1) in kinds


def test_global_ring_enable_disable(monkeypatch):
    assert obs_telemetry.get_ring() is None     # default: off
    monkeypatch.setenv("SLT_TELEMETRY", "true")
    ring = obs_telemetry.maybe_enable_from_env(
        lambda: {"counters": {}}, party="p")
    try:
        assert obs_telemetry.get_ring() is ring
        assert obs_telemetry.enabled()
    finally:
        obs_telemetry.disable()
    assert obs_telemetry.get_ring() is None


def test_serve_telemetry_endpoint():
    state = {"counters": {"c": 0.0}}
    ring, clk = _ring_over(state, interval_s=0.05)
    srv, _thread = serve_telemetry(ring, port=0)
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}/telemetry"
        with urllib.request.urlopen(url, timeout=5) as resp:
            body = json.loads(resp.read())
        assert body["kind"] == "slt-telemetry"
        assert body["party"] == "test"
        with pytest.raises(Exception):
            urllib.request.urlopen(
                url.replace("/telemetry", "/other"), timeout=5)
    finally:
        srv.shutdown()


def test_http_server_telemetry_route():
    """transport/http.py serves /telemetry for ANY runtime role (404
    when telemetry is off, the ring dump when a per-server ring is
    attached) and stage-role /health carries uptime_seconds + build."""
    import jax
    from split_learning_tpu.models import get_plan
    from split_learning_tpu.runtime.stage import StageRuntime
    from split_learning_tpu.transport.http import SplitHTTPServer
    from split_learning_tpu.utils import Config

    batch = 4
    cfg = Config(mode="split", model="split_cnn_chain3",
                 batch_size=batch, num_stages=3, microbatches=1)
    plan = get_plan(model="split_cnn_chain3", mode="split")
    sample = np.zeros((batch, 28, 28, 1), np.float32)
    stage = StageRuntime(plan, 1, cfg, jax.random.PRNGKey(0), sample,
                         microbatches=1, apply_lag=0)
    state = {"counters": {"c": 1.0}}
    ring, clk = _ring_over(state, interval_s=0.01)
    clk.t = 1.0
    off = SplitHTTPServer(stage).start()
    on = SplitHTTPServer(stage, telemetry=ring).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{off.url}/telemetry", timeout=5)
        assert err.value.code == 404
        with urllib.request.urlopen(f"{on.url}/telemetry",
                                    timeout=5) as resp:
            body = json.loads(resp.read())
        assert body["kind"] == "slt-telemetry"
        assert body["windows"]
        from split_learning_tpu.transport import codec
        with urllib.request.urlopen(f"{on.url}/health",
                                    timeout=5) as resp:
            health = codec.decode(resp.read())
        assert "uptime_seconds" in health and "version" in health
        with urllib.request.urlopen(f"{on.url}/metrics",
                                    timeout=5) as resp:
            text = resp.read().decode()
        assert "slt_uptime_seconds" in text
        assert "slt_stage_index" in text
    finally:
        off.stop()
        on.stop()
        stage.close()


def _chain_losses(telemetry: bool, steps: int = 3):
    import jax
    from split_learning_tpu.models import get_plan
    from split_learning_tpu.obs.metrics import Registry
    from split_learning_tpu.runtime.pipeline_runner import PipelineRunner
    from split_learning_tpu.runtime.stage import StageRuntime
    from split_learning_tpu.transport.local import LocalTransport
    from split_learning_tpu.utils import Config

    batch = 8
    cfg = Config(mode="split", model="split_cnn_chain3",
                 batch_size=batch, num_stages=3, microbatches=1,
                 seed=2)
    plan = get_plan(model="split_cnn_chain3", mode="split")
    sample = np.zeros((batch, 28, 28, 1), np.float32)
    if telemetry:
        obs_trace.enable()
    stages = [StageRuntime(plan, i, cfg, jax.random.PRNGKey(2), sample,
                           microbatches=1, apply_lag=0)
              for i in (1, 2)]
    runner = PipelineRunner(plan, cfg, jax.random.PRNGKey(2), sample,
                            [LocalTransport(s) for s in stages],
                            microbatches=1)
    rings = []
    try:
        if telemetry:
            hub_reg = Registry()
            runner.telemetry_registry = hub_reg
            rings = [TelemetryRing(hub_reg.snapshot, party="hub",
                                   interval_s=0.01)]
            rings += [TelemetryRing(s.metrics,
                                    party=f"stage{s.stage_index}",
                                    interval_s=0.01) for s in stages]
        losses = []
        rs = np.random.RandomState(5)
        for i in range(steps):
            x = rs.randn(batch, 28, 28, 1).astype(np.float32)
            y = rs.randint(0, 10, batch).astype(np.int64)
            losses.append(runner.step(x, y, i))
            for ring in rings:
                ring.advance(force=True)
    finally:
        runner.close()
        for s in stages:
            s.close()
        if telemetry:
            obs_trace.disable()
    return losses, rings


@pytest.mark.slow
def test_telemetry_off_is_bit_identical_to_on():
    """The zero-overhead-off pin, stated as arithmetic: a chain run with
    telemetry fully off produces the exact same loss series as a twin
    with the tracer on, per-party rings attached to every runtime, and
    the rings force-advanced after every step. The plane observes; it
    never participates."""
    assert obs_telemetry.get_ring() is None
    assert obs_trace.get_tracer() is None
    base, _ = _chain_losses(telemetry=False)
    on, rings = _chain_losses(telemetry=True)
    assert base == on                    # bit-for-bit, not approx
    # and the on-twin actually measured something
    hub_windows = rings[0].windows()
    assert sum(w["counters"].get("hub_steps_total", 0)
               for w in hub_windows) == 3
    stage_counts = sum(
        w["histograms"].get(spans.DISPATCH, {}).get("count", 0)
        for w in rings[1].windows())
    assert stage_counts > 0
