"""bench.py contract: the driver parses exactly one JSON line
{"metric", "value", "unit", "vs_baseline"} from stdout. A broken bench
means an unscored round, so the contract gets its own test (hermetic: the
subprocesses inherit this env's CPU-forced JAX)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_quick_prints_contract_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--quick"],
        capture_output=True, text=True, timeout=900, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    json_lines = [l for l in out.stdout.splitlines()
                  if l.strip().startswith("{")]
    assert len(json_lines) == 1, out.stdout
    rec = json.loads(json_lines[0])
    assert rec["metric"] == "mnist_split_cnn_steps_per_sec"
    assert rec["unit"] == "steps/sec"
    assert rec["value"] and rec["value"] > 0
    assert rec["vs_baseline"] and rec["vs_baseline"] > 1
