"""bench.py contract: the driver parses exactly one JSON line
{"metric", "value", "unit", "vs_baseline"} from stdout. A broken bench
means an unscored round, so the contract gets its own test (hermetic: the
subprocesses inherit this env's CPU-forced JAX)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_quick_prints_contract_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--quick"],
        capture_output=True, text=True, timeout=900, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    json_lines = [l for l in out.stdout.splitlines()
                  if l.strip().startswith("{")]
    assert len(json_lines) == 1, out.stdout
    rec = json.loads(json_lines[0])
    assert rec["metric"] == "mnist_split_cnn_steps_per_sec"
    assert rec["unit"] == "steps/sec"
    assert rec["value"] and rec["value"] > 0
    assert rec["vs_baseline"] and rec["vs_baseline"] > 1
    # the fused leg in the detail line must have passed the publication
    # gate: physically possible throughput + work-scaling timed window
    detail_lines = [l for l in out.stderr.splitlines()
                    if l.startswith("[bench] detail:")]
    assert detail_lines, out.stderr[-2000:]
    fused = json.loads(detail_lines[0].split("detail:", 1)[1])["fused"]
    assert fused["valid"] is True
    util = fused.get("util_vs_bf16_peak")
    assert util is None or util <= 1.0
    assert 1.5 <= fused["linearity_2x"] <= 2.6


def test_bench_wire_and_pipelined_roles_quick():
    """The side legs the orchestrator adds in non-quick runs must at
    least produce their contract fields (run here in quick mode,
    in-process on the CPU-forced test env)."""
    sys.path.insert(0, REPO)
    from bench import measure_pipelined, measure_wire

    wire = measure_wire(quick=True)
    assert wire["valid"] and wire["byte_reduction"] > 3.5
    assert wire["p50_ms_none"] > 1.0 and wire["p50_ms_int8"] > 1.0

    piped = measure_pipelined(quick=True)
    assert piped["valid"]
    assert piped["steps_per_sec_sync"] > 0
    assert piped["steps_per_sec_depth4"] > 0
    assert "note" in piped  # the shared-core caveat must ship with the leg


def test_validate_leg_gates_impossible_throughput():
    """The round-1/2 failure mode — a steps/sec figure above chip peak —
    must be refused, whether the peak is known (util>1) or not (absolute
    TFLOP/s bound); a dispatch-only timer must be caught by linearity."""
    sys.path.insert(0, REPO)
    from bench import validate_leg

    ok, reason = validate_leg({"util_vs_bf16_peak": 0.10,
                               "model_tflops_per_sec": 20.0,
                               "linearity_2x": 1.9})
    assert ok and reason is None

    # round-2's actual artifact: 60.5x peak
    ok, reason = validate_leg({"util_vs_bf16_peak": 60.53,
                               "model_tflops_per_sec": 11925.0,
                               "linearity_2x": 1.9})
    assert not ok and "peak" in reason

    # unknown peak (CPU): absolute bound
    ok, reason = validate_leg({"util_vs_bf16_peak": None,
                               "model_tflops_per_sec": 500.0,
                               "linearity_2x": 2.0})
    assert not ok and "5 TFLOP/s" in reason

    # dispatch-only timer: doubling the work doesn't double the window
    ok, reason = validate_leg({"util_vs_bf16_peak": 0.5,
                               "model_tflops_per_sec": 1.0,
                               "linearity_2x": 1.02})
    assert not ok and "linearity" in reason
