"""bench.py contract: the driver parses exactly one JSON line
{"metric", "value", "unit", "vs_baseline"} from stdout. A broken bench
means an unscored round, so the contract gets its own test (hermetic: the
subprocesses inherit this env's CPU-forced JAX)."""

import json
import os
import subprocess
import sys
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_quick_prints_contract_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--quick"],
        capture_output=True, text=True, timeout=900, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    json_lines = [l for l in out.stdout.splitlines()
                  if l.strip().startswith("{")]
    assert len(json_lines) == 1, out.stdout
    rec = json.loads(json_lines[0])
    assert rec["metric"] == "mnist_split_cnn_steps_per_sec"
    assert rec["unit"] == "steps/sec"
    assert rec["value"] and rec["value"] > 0
    assert rec["vs_baseline"] and rec["vs_baseline"] > 1
    # the fused leg in the detail line must have passed the publication
    # gate: physically possible throughput + work-scaling timed window
    detail_lines = [l for l in out.stderr.splitlines()
                    if l.startswith("[bench] detail:")]
    assert detail_lines, out.stderr[-2000:]
    fused = json.loads(detail_lines[0].split("detail:", 1)[1])["fused"]
    assert fused["valid"] is True
    util = fused.get("util_vs_bf16_peak")
    assert util is None or util <= 1.0
    assert 1.5 <= fused["linearity_2x"] <= 2.6


@pytest.mark.slow
def test_bench_wire_and_pipelined_roles_quick():
    """The side legs the orchestrator adds in non-quick runs must at
    least produce their contract fields (run here in quick mode,
    in-process on the CPU-forced test env)."""
    sys.path.insert(0, REPO)
    from bench import measure_pipelined, measure_wire

    wire = measure_wire(quick=True)
    assert wire["valid"] and wire["byte_reduction"] > 3.5
    assert wire["p50_ms_none"] > 1.0 and wire["p50_ms_int8"] > 1.0

    piped = measure_pipelined(quick=True)
    assert piped["valid"]
    assert piped["steps_per_sec_sync"] > 0
    assert piped["steps_per_sec_depth4"] > 0
    assert "note" in piped  # the shared-core caveat must ship with the leg
    # the depth-W window's benefit, demonstrated: with wire latency
    # injected (sleeps burn no CPU, so one core suffices) the window
    # hides the wire behind compute, which the lock-step loop cannot.
    # Loose bound: quick mode times only 6 steps and the image's CPU
    # timing is load-sensitive; the full bench leg (20 steps) publishes
    # the real figure (~1.5x).
    syn_wire = piped["synthetic_wire"]
    assert syn_wire["pipelining_speedup"] > 1.1, syn_wire
    assert "synthetic" in syn_wire["note"]


@pytest.mark.slow
def test_bench_topk8_role_quick():
    """The wire_topk8 leg's contract fields (satellite of the sparse
    error-feedback compression PR): per-mode bytes/step and losses, the
    two byte-reduction ratios against the gates the full leg publishes
    (>=8x vs fp32, >=2.5x vs int8), and loss_parity. The parity gate
    itself only binds the 300-step full leg — 40 quick steps end
    mid-descent — so quick mode must still gate bytes but not parity."""
    sys.path.insert(0, REPO)
    from bench import measure_topk8

    tk = measure_topk8(quick=True)
    assert tk["leg"] == "wire_topk8"
    assert tk["density"] == 0.1
    for mode in ("none", "int8", "topk8"):
        assert tk[f"bytes_per_step_{mode}"] > 0
        assert tk[f"final_loss_{mode}"] > 0
        assert tk[f"steps_per_sec_{mode}"] > 0
    assert tk["bytes_per_step"] == tk["bytes_per_step_topk8"]
    assert tk["byte_reduction_vs_fp32"] >= 8.0
    assert tk["byte_reduction_vs_int8"] >= 2.5
    assert tk["loss_parity"] >= 0.0
    # the byte gates bind even in quick mode: a broken encoder (say, the
    # bitmap path regressing to int32 indices) must fail here, not only
    # in the 15-minute full leg
    assert tk["valid"] is True, tk["invalid_reason"]
    assert "synthetic-wire" in tk["platform"]
    # dispatch watchdog rode along: the leg compiled its jits once and
    # never retraced in steady state (gated into valid above)
    cc = tk["compile_count"]
    assert cc["total"] >= 1
    assert cc["steady_state"] == 0


@pytest.mark.slow
def test_bench_coalesced_compile_count_quick():
    """The multi_client_coalesced leg publishes per-leg compile counts
    from the dispatch watchdog (obs/dispatch_debug.py, forced in-process
    for the timed runs) and gates steady-state recompiles at 0 — the
    pow2-padded group signatures must hold across every occupancy."""
    sys.path.insert(0, REPO)
    from bench import measure_coalesced

    co = measure_coalesced(quick=True)
    assert co["leg"] == "multi_client_coalesced"
    cc = co["compile_count"]
    assert cc["total"] >= 1
    assert cc["steady_state"] == 0
    assert co["valid"] is True, co["invalid_reason"]


@pytest.mark.slow
def test_bench_chaos_soak_role_quick():
    """The chaos_soak leg's contract fields (robustness PR): trains the
    same seeded stream clean and under a seeded drop_resp/dup/http500
    schedule, and must report zero dropped batches, engaged replay
    cache, injected faults, and (exactly-once being deterministic) a
    loss parity that binds even in quick mode."""
    sys.path.insert(0, REPO)
    from bench import measure_chaos_soak

    soak = measure_chaos_soak(quick=True)
    assert soak["leg"] == "chaos_soak"
    assert soak["platform"] == "cpu"
    assert soak["chaos_spec"] and soak["chaos_seed"] is not None
    assert soak["dropped_batches"] == 0
    assert sum(soak["chaos_injected"].values()) > 0
    assert soak["replay_hits"] > 0
    for run in ("clean", "chaos"):
        assert soak[f"final_loss_{run}"] > 0
        assert soak[f"steps_per_sec_{run}"] > 0
    assert soak["loss_parity"] <= 0.05
    assert soak["max_step_loss_diff"] >= 0.0
    assert soak["valid"] is True, soak["invalid_reason"]


@pytest.mark.slow
def test_bench_fleet_soak_role_quick():
    """The fleet_soak leg's contract fields (continuous batching PR):
    one seeded bursty arrival schedule offered to window, continuous,
    and chaos-wrapped-continuous twins. Gates: every scheduled step
    completes, continuous p99 pooled queue-wait beats window, the
    measured runs see zero XLA compiles (warm_fleet shape priming), and
    the chaos twin's loss stays with its clean twin."""
    sys.path.insert(0, REPO)
    from bench import measure_fleet_soak

    fs = measure_fleet_soak(quick=True)
    assert fs["leg"] == "fleet_soak"
    assert fs["clients"] >= 64 and fs["tenants"] >= 2
    expected = fs["clients"] * fs["steps_per_client"]
    for tag in ("window", "continuous", "chaos_twin"):
        rec = fs[tag]
        assert rec["steps_completed"] == expected
        assert rec["dropped_steps"] == 0
        assert rec["compiles_in_run"] == 0
        assert rec["steady_state_recompiles"] == 0
        assert rec["overall"]["queue_wait_p99_ms"] > 0
        assert rec["mean_occupancy"] >= 1.0
        assert len(rec["per_tenant"]) == fs["tenants"]
    assert (fs["queue_wait_p99_ms_continuous"]
            < fs["queue_wait_p99_ms_window"])
    assert fs["chaos_twin"]["replay"]["replay_hits"] > 0
    assert fs["loss_parity"] <= 0.05  # absolute nats (the leg's own gate)
    assert fs["valid"] is True, fs["invalid_reason"]


@pytest.mark.slow
def test_bench_reply_latency_2bp_role_quick():
    """The reply_latency_2bp leg's contract fields (2BP PR): 4
    free-running clients over heterogeneous synthetic wires against a
    coupled vs decoupled server. Gates carried by the leg itself:
    decoupled reply p50 <= 0.7x coupled, lag=0 bit-identity, lag=2
    staleness within the stated nats budget, zero steady-state
    recompiles across both decoupled programs."""
    sys.path.insert(0, REPO)
    from bench import measure_reply_latency_2bp

    rl = measure_reply_latency_2bp(quick=True)
    assert rl["leg"] == "reply_latency_2bp"
    assert rl["clients"] == 4
    assert rl["apply_lag"] == 2
    assert rl["model"]["lm"] is True and rl["model"]["vocab"] >= 1024
    assert len(rl["one_way_latency_ms"]) == rl["clients"]
    assert rl["reply_p50_ms_coupled"] > 0
    assert rl["reply_p50_ms_decoupled"] > 0
    assert rl["reply_p50_ratio"] <= 0.7
    assert rl["reply_p90_ms_coupled"] >= rl["reply_p50_ms_coupled"]
    assert rl["reply_p90_ms_decoupled"] >= rl["reply_p50_ms_decoupled"]
    assert rl["loss_lag0_max_abs_diff"] == 0.0
    assert rl["loss_lag2_staleness_nats"] <= rl["nats_budget"]
    ctr = rl["decoupled_counters"]
    assert ctr["deferred_enqueued"] > 0
    assert ctr["deferred_applied"] + ctr["deferred_apply_depth"] == \
        ctr["deferred_enqueued"]
    assert rl["compile_count"]["steady_state"] == 0
    assert rl["valid"] is True, rl["invalid_reason"]


@pytest.mark.slow
def test_bench_sharded_server_role_quick():
    """The sharded_server leg's contract fields (pjit PR): 8 concurrent
    clients against data=1 vs data=2 coalescing servers at the same
    per-device row ceiling, on the conftest-forced 8-device topology.
    Gates carried by the leg itself: mesh=1 bit-identity, data=2 float
    parity, data=2 strictly-higher throughput at strictly-higher group
    occupancy, zero steady-state recompiles, and the mesh/MFU metadata
    present with MFU honestly None on the CPU backend."""
    sys.path.insert(0, REPO)
    from bench import measure_sharded_server

    sh = measure_sharded_server(quick=True)
    assert sh["leg"] == "sharded_server"
    assert sh["valid"] is True, sh["invalid_reason"]
    assert sh["batch_ceiling_relative"] is True
    assert "ceiling" in sh["note"]  # the honesty caveat ships with the leg
    assert sh["mesh"]["devices"] == 2 and sh["mesh"]["data"] == 2
    assert sh["coalesce_max"]["data2"] == 2 * sh["coalesce_max"]["data1"]
    assert sh["steps_per_sec_data2"] > sh["steps_per_sec_data1"] > 0
    assert sh["mean_occupancy_data2"] > sh["mean_occupancy_data1"]
    assert sh["loss_mesh1_max_abs_diff"] == 0.0
    assert sh["loss_data2_max_abs_diff"] <= sh["parity_tol"]
    assert sh["compile_count"]["steady_state"] == 0
    assert sh["gather_bytes"] > 0
    assert sh["peak_flops_per_device"] is None  # CPU: unknown, never 0
    progs = sh["programs"]
    assert progs and all(p["calls"] >= 1 and p["mfu"] is None
                         for p in progs.values())


def test_degraded_headline_is_self_describing(monkeypatch, capsys):
    """VERDICT r3 weak #1: when the intended TPU backend is unavailable
    the parsed headline must never be a bare CPU number — it replays the
    newest committed gated TPU artifact (provenance marked) or publishes
    null + reason."""
    sys.path.insert(0, REPO)
    from bench import (_emit_degraded_headline, _latest_tpu_artifact,
                       _tpu_intended)

    # intent detection: explicit cpu pin is honest-CPU, axon env is TPU
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    assert not _tpu_intended()
    monkeypatch.delenv("JAX_PLATFORMS")
    assert _tpu_intended()
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS")
    assert not _tpu_intended()

    art = _latest_tpu_artifact()
    assert art is not None, "committed gated TPU artifact must exist"
    path, rec = art
    assert rec["fused"]["valid"] and rec["fused"]["platform"] == "tpu"

    fused_cpu = {"steps_per_sec": 6.14, "platform": "cpu"}
    assert _emit_degraded_headline(fused_cpu) is True
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["degraded"] is True
    assert out["provenance"] == "replayed-from-artifact"
    assert out["platform"] == "tpu"
    assert out["artifact"] == path
    assert out["value"] == rec["headline"]["value"]
    assert out["cpu_fallback_steps_per_sec"] == 6.14

    # with no artifact available: null value + reason, never the CPU number
    monkeypatch.setattr("bench._latest_tpu_artifact", lambda: None)
    assert _emit_degraded_headline(fused_cpu) is False
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["degraded"] is True and out["value"] is None
    assert "degraded_reason" in out


def test_validate_leg_gates_impossible_throughput():
    """The round-1/2 failure mode — a steps/sec figure above chip peak —
    must be refused, whether the peak is known (util>1) or not (absolute
    TFLOP/s bound); a dispatch-only timer must be caught by linearity."""
    sys.path.insert(0, REPO)
    from bench import validate_leg

    ok, reason = validate_leg({"util_vs_bf16_peak": 0.10,
                               "model_tflops_per_sec": 20.0,
                               "linearity_2x": 1.9})
    assert ok and reason is None

    # round-2's actual artifact: 60.5x peak
    ok, reason = validate_leg({"util_vs_bf16_peak": 60.53,
                               "model_tflops_per_sec": 11925.0,
                               "linearity_2x": 1.9})
    assert not ok and "peak" in reason

    # unknown peak (CPU): absolute bound
    ok, reason = validate_leg({"util_vs_bf16_peak": None,
                               "model_tflops_per_sec": 500.0,
                               "linearity_2x": 2.0})
    assert not ok and "5 TFLOP/s" in reason

    # dispatch-only timer: doubling the work doesn't double the window
    ok, reason = validate_leg({"util_vs_bf16_peak": 0.5,
                               "model_tflops_per_sec": 1.0,
                               "linearity_2x": 1.02})
    assert not ok and "linearity" in reason


def test_grow_window_clears_timing_floor():
    """The fused role's timed window must dwarf the fixed per-window
    close-out cost (the 2026-07-31 quick CNN leg timed 0.07 s windows
    and failed its linearity gate at 1.37): grow_window doubles the
    chunk count until a *measured* window clears the floor."""
    import bench

    calls = []

    def fake_window(n):  # 50 ms fixed cost + 20 ms/chunk "compute"
        calls.append(n)
        return 0.05 + 0.02 * n, 0.0

    n = bench.grow_window(fake_window, 2, floor_s=1.0)
    assert n == 64                      # 0.05 + 1.28 s clears the floor
    assert calls == [2, 4, 8, 16, 32, 64]
    # an already-long window is left alone
    assert bench.grow_window(lambda n: (5.0, 0.0), 4, floor_s=1.0) == 4
    # the cap bounds pathological growth
    assert bench.grow_window(lambda n: (0.0, 0.0), 2, floor_s=1.0,
                             cap=16) == 16


def test_headline_route_priority():
    """Replay-over-null (round-5 fix): a wedged tunnel's CPU fallback
    leg routes to the degraded replay even when that fallback's OWN
    linearity flaked invalid (observed 2026-08-01: contention put the
    CPU context leg at 1.23 and the old ordering nulled a round that
    had a committed gated TPU artifact to replay). The validity gate
    still nulls measurements on the intended platform."""
    sys.path.insert(0, REPO)
    import bench

    cpu_invalid = {"platform": "cpu", "valid": False,
                   "invalid_reason": "linearity_2x=1.23 ..."}
    cpu_valid = {"platform": "cpu", "valid": True}
    tpu_invalid = {"platform": "tpu", "valid": False,
                   "invalid_reason": "linearity"}
    tpu_valid = {"platform": "tpu", "valid": True}

    real = bench._tpu_intended
    try:
        bench._tpu_intended = lambda: True   # a tunnel exists here
        assert bench.headline_route(cpu_invalid) == "degraded"
        assert bench.headline_route(cpu_valid) == "degraded"
        assert bench.headline_route(tpu_invalid) == "invalid"
        assert bench.headline_route(tpu_valid) == "publish"

        bench._tpu_intended = lambda: False  # CPU-only host: CPU is honest
        assert bench.headline_route(cpu_invalid) == "invalid"
        assert bench.headline_route(cpu_valid) == "publish"
    finally:
        bench._tpu_intended = real


def test_bench_d_model_guard(monkeypatch):
    """SLT_BENCH_DMODEL must be a multiple of 128: heads scale with
    width so head_dim stays the 128-lane tile the recorded flash_block
    is resolved for — a non-multiple would silently benchmark a
    different kernel shape than the record describes."""
    sys.path.insert(0, REPO)
    from bench import _bench_d_model
    monkeypatch.delenv("SLT_BENCH_DMODEL", raising=False)
    assert _bench_d_model() == 256
    monkeypatch.setenv("SLT_BENCH_DMODEL", "1024")
    assert _bench_d_model() == 1024
    monkeypatch.setenv("SLT_BENCH_DMODEL", "320")
    with pytest.raises(SystemExit):
        _bench_d_model()


def test_transformer_trunk_kwargs_contract(monkeypatch):
    """The shared trunk builder (bench.transformer_trunk_kwargs) is
    what both the legs and the profiler build from: heads must scale
    with width so head_dim stays the 128-lane tile, and the max_len
    floor must track the seq knob."""
    import numpy as np
    sys.path.insert(0, REPO)
    from bench import transformer_trunk_kwargs
    monkeypatch.delenv("SLT_BENCH_DMODEL", raising=False)
    monkeypatch.delenv("SLT_BENCH_SEQ", raising=False)
    kw = transformer_trunk_kwargs("split", "bfloat16")
    assert kw["d_model"] == 256 and kw["num_heads"] == 2
    assert kw["d_model"] // kw["num_heads"] == 128
    assert kw["max_len"] == 2048
    assert kw["dtype"] == np.dtype("bfloat16")
    monkeypatch.setenv("SLT_BENCH_DMODEL", "1024")
    monkeypatch.setenv("SLT_BENCH_SEQ", "8192")
    kw = transformer_trunk_kwargs("split", "float32")
    assert kw["num_heads"] == 8 and kw["d_model"] // kw["num_heads"] == 128
    assert kw["max_len"] == 8192


def test_fleet_sim_summary_utilization_schema(monkeypatch, capsys):
    """scripts/fleet_sim.py's JSON summary carries the utilization /
    saturation block capacity sweeps bisect on: steady-state occupancy
    as a fraction of --coalesce-max, the admission reject rate, and the
    pooled step p99 measured against --slo-ms. Run in-process (the
    suite's JAX is already warm) on a tiny quota'd fleet so every field
    takes its non-null arm."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "fleet_sim", os.path.join(REPO, "scripts", "fleet_sim.py"))
    fleet_sim = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fleet_sim)

    monkeypatch.setattr(sys, "argv", [
        "fleet_sim.py", "--clients", "4", "--tenants", "2",
        "--steps", "1", "--rate", "5.0", "--batch", "4",
        "--batching", "continuous", "--coalesce-max", "4",
        "--quota", "100", "--slo-ms", "5000"])
    assert fleet_sim.main() == 0
    out = capsys.readouterr().out
    summary = json.loads(out[out.index("{"):])

    util = summary["utilization"]
    assert set(util) == {"mean_occupancy", "steady_state_occupancy",
                         "admission_reject_rate", "step_p99_over_slo",
                         "slo_attained"}
    assert util["mean_occupancy"] >= 1.0
    assert 0.0 < util["steady_state_occupancy"] <= 1.0
    assert util["steady_state_occupancy"] == pytest.approx(
        util["mean_occupancy"] / 4, abs=5e-4)
    # quota'd run: the admission layer is live, so the rate is a number
    assert 0.0 <= util["admission_reject_rate"] <= 1.0
    assert util["step_p99_over_slo"] > 0.0
    assert util["slo_attained"] == (util["step_p99_over_slo"] <= 1.0)
    # without --quota/--slo-ms the null arms must ship as nulls, not be
    # dropped from the schema (jq-stable for sweep scripts)
    monkeypatch.setattr(sys, "argv", [
        "fleet_sim.py", "--clients", "2", "--tenants", "1",
        "--steps", "1", "--rate", "5.0", "--batch", "4",
        "--batching", "continuous"])
    assert fleet_sim.main() == 0
    out = capsys.readouterr().out
    summary = json.loads(out[out.index("{"):])
    util = summary["utilization"]
    assert util["admission_reject_rate"] is None
    assert util["step_p99_over_slo"] is None
    assert util["slo_attained"] is None


@pytest.mark.slow
def test_bench_replica_failover_role_quick():
    """The replica_failover side leg (in-process, quick): twin
    3-replica groups, one chaos-killed mid-run — the contract fields
    the orchestrator publishes plus every gate it enforces."""
    sys.path.insert(0, REPO)
    from bench import measure_replica_failover

    rec = measure_replica_failover(quick=True)
    assert rec["valid"], rec["invalid_reason"]
    assert rec["replicas_one_bit_identical"] is True
    expected = rec["clients"] * rec["steps_per_client"]
    for tag in ("clean", "killed"):
        assert rec[tag]["steps_completed"] == expected
        assert rec[tag]["dropped_steps"] == 0
        assert rec[tag]["steady_state_recompiles"] == 0
    assert rec["clean"]["kills"] == 0
    assert rec["killed"]["kills"] == 1
    assert rec["killed"]["replica_handoffs"] == 1
    assert rec["killed"]["handoff_replay_entries"] > 0
    assert rec["killed"]["replica_reroutes"] > 0
    assert len(rec["killed"]["live_replicas"]) == rec["replicas"] - 1
    assert rec["loss_parity"] <= 0.25


REPLICATION_KEYS = {"replicas", "kill_replica_at", "kills",
                    "live_replicas", "handoff", "reroute_wait",
                    "handoff_latency", "per_replica", "replica_seconds"}
HANDOFF_KEYS = {"replica_routes", "replica_reroutes", "replica_deaths",
                "replica_handoffs", "handoff_replay_entries",
                "handoff_ef_entries", "handoff_deferred_flushed",
                "replica_syncs", "replica_fenced_waits"}


def test_fleet_sim_replication_schema(monkeypatch, capsys):
    """The ``replication`` block is schema-stable across arms: a
    --replicas 1 run ships the same keys with zeroed handoff counters,
    null latency tails and an empty per-replica list; a chaos-kill run
    ships engaged counters, the surviving router view, and per-replica
    replay detail — so a twin-run diff never branches on shape."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "fleet_sim_repl", os.path.join(REPO, "scripts", "fleet_sim.py"))
    fleet_sim = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fleet_sim)

    # null arm: plain server, nothing killed
    monkeypatch.setattr(sys, "argv", [
        "fleet_sim.py", "--clients", "2", "--steps", "1",
        "--rate", "5.0", "--batch", "4", "--workers", "2"])
    assert fleet_sim.main() == 0
    out = capsys.readouterr().out
    null_arm = json.loads(out[out.index("{"):])["replication"]
    assert set(null_arm) == REPLICATION_KEYS
    assert set(null_arm["handoff"]) == HANDOFF_KEYS
    assert null_arm["replicas"] == 1 and null_arm["kills"] == 0
    assert null_arm["live_replicas"] == [0]
    assert all(v == 0 for v in null_arm["handoff"].values())
    assert null_arm["reroute_wait"] == {"p50_ms": None, "p99_ms": None}
    assert null_arm["handoff_latency"] == {"p50_ms": None,
                                           "p99_ms": None}
    assert null_arm["per_replica"] == []
    # the one bare replica is alive for the whole run
    assert null_arm["replica_seconds"] > 0

    # chaos-kill arm: 2 replicas, kill the busiest mid-run
    monkeypatch.setattr(sys, "argv", [
        "fleet_sim.py", "--clients", "6", "--steps", "2",
        "--rate", "5.0", "--batch", "4", "--workers", "4",
        "--replicas", "2", "--kill-replica-at", "4",
        "--gate-dropped-steps"])
    assert fleet_sim.main() == 0
    out = capsys.readouterr().out
    summary = json.loads(out[out.index("{"):])
    kill_arm = summary["replication"]
    assert set(kill_arm) == REPLICATION_KEYS
    assert set(kill_arm["handoff"]) == HANDOFF_KEYS
    assert kill_arm["replicas"] == 2 and kill_arm["kills"] == 1
    assert len(kill_arm["live_replicas"]) == 1
    assert kill_arm["handoff"]["replica_deaths"] == 1
    assert kill_arm["handoff"]["replica_handoffs"] == 1
    assert kill_arm["handoff"]["replica_routes"] > 0
    assert kill_arm["handoff_latency"]["p50_ms"] is not None
    rows = kill_arm["per_replica"]
    assert [r["replica"] for r in rows] == [0, 1]
    assert sum(r["alive"] for r in rows) == 1
    # per-replica alive windows: the killed one stopped accruing, and
    # the group total is the sum of the per-replica windows
    assert all(r["alive_s"] >= 0 for r in rows)
    assert kill_arm["replica_seconds"] == pytest.approx(
        sum(r["alive_s"] for r in rows), abs=0.01)
    # gate held through the kill: every scheduled step completed
    assert summary["dropped_steps"] == 0
    assert summary["steps_completed"] == summary["steps_expected"]

    # --kill-replica-at without replication is a usage error, not a hang
    monkeypatch.setattr(sys, "argv", [
        "fleet_sim.py", "--clients", "2", "--kill-replica-at", "1"])
    assert fleet_sim.main() == 2


AUTOSCALE_KEYS = {"enabled", "min_replicas", "max_replicas",
                  "cooldown_s", "decisions", "scale_ups", "scale_downs",
                  "events", "replica_seconds",
                  "static_peak_replica_seconds", "peak_replicas",
                  "final_replicas", "p99_ms_trajectory"}


def test_fleet_sim_summary_autoscale_schema(monkeypatch, capsys):
    """The ``autoscale`` block is schema-stable across arms: an elastic
    run ships the policy config, the scale-event log, replica-seconds
    against the static-peak counterfactual and the policy-seen p99
    trajectory; a run without --autoscale ships the same keys with the
    false/empty/null arm — and constructs no policy at all (the
    zero-overhead-off pin)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "fleet_sim_as", os.path.join(REPO, "scripts", "fleet_sim.py"))
    fleet_sim = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fleet_sim)

    # elastic arm: short windows + a fast cooldown so the pump gets
    # several evaluations inside even a tiny run
    monkeypatch.setattr(sys, "argv", [
        "fleet_sim.py", "--clients", "4", "--steps", "2",
        "--rate", "5.0", "--batch", "4", "--workers", "4",
        "--autoscale", "--autoscale-min", "1", "--autoscale-max", "2",
        "--autoscale-cooldown-s", "0.1",
        "--telemetry-interval-s", "0.1"])
    assert fleet_sim.main() == 0
    out = capsys.readouterr().out
    summary = json.loads(out[out.index("{"):])
    block = summary["autoscale"]
    assert set(block) == AUTOSCALE_KEYS
    assert block["enabled"] is True
    assert block["min_replicas"] == 1 and block["max_replicas"] == 2
    assert block["cooldown_s"] == pytest.approx(0.1)
    assert block["decisions"] >= 1
    assert block["replica_seconds"] > 0
    # the counterfactual is peak * run-wall; replica_seconds spans the
    # group's whole lifetime (warmup included), so only sign-check here
    assert block["static_peak_replica_seconds"] > 0
    assert block["peak_replicas"] >= 1
    assert block["final_replicas"] >= 1
    for ev in block["events"]:
        assert set(ev) == {"t_s", "window", "direction", "reason",
                           "replica", "n_live"}
        assert ev["direction"] in ("up", "down")
    # the elastic arm fronts a group even at one replica, so the
    # replication block reports through the router view
    assert summary["replication"]["replicas"] >= 1
    assert summary["config"]["autoscale"] is True

    # null arm: same keys, false/empty/null values — exact dict
    monkeypatch.setattr(sys, "argv", [
        "fleet_sim.py", "--clients", "2", "--steps", "1",
        "--rate", "5.0", "--batch", "4"])
    assert fleet_sim.main() == 0
    out = capsys.readouterr().out
    summary = json.loads(out[out.index("{"):])
    assert summary["autoscale"] == {
        "enabled": False, "min_replicas": None, "max_replicas": None,
        "cooldown_s": None, "decisions": 0, "scale_ups": 0,
        "scale_downs": 0, "events": [], "replica_seconds": None,
        "static_peak_replica_seconds": None, "peak_replicas": None,
        "final_replicas": None, "p99_ms_trajectory": []}
    assert summary["config"]["autoscale"] is False

    # --gate-autoscale without --autoscale is a usage error, not a hang
    monkeypatch.setattr(sys, "argv", [
        "fleet_sim.py", "--clients", "2", "--gate-autoscale"])
    assert fleet_sim.main() == 2


@pytest.mark.slow
def test_bench_autoscale_diurnal_role_quick():
    """The autoscale_diurnal side leg (in-process, quick): static-peak
    vs elastic twins over one seeded diurnal schedule — the contract
    fields the orchestrator publishes plus every gate it enforces."""
    sys.path.insert(0, REPO)
    from bench import measure_autoscale_diurnal

    rec = measure_autoscale_diurnal(quick=True)
    assert rec["valid"], rec["invalid_reason"]
    expected = rec["clients"] * rec["steps_per_client"]
    for tag in ("static", "elastic"):
        assert rec[tag]["steps_completed"] == expected
        assert rec[tag]["dropped_steps"] == 0
    assert rec["static"]["scale_ups"] == 0
    assert rec["elastic"]["scale_ups"] >= 1
    assert rec["elastic"]["settled_p99_ms"] is not None
    assert rec["elastic"]["settled_p99_ms"] <= rec["slo_ms"]
    assert rec["elastic"]["replica_seconds"] < \
        rec["static"]["replica_seconds"]
    assert rec["replica_seconds_saved"] > 0


TELEMETRY_KEYS = {"enabled", "interval_s", "windows",
                  "p99_ms_trajectory", "burn_peak", "slo_alerts",
                  "bottleneck_histogram"}


def test_fleet_sim_summary_telemetry_schema(monkeypatch, capsys):
    """scripts/fleet_sim.py's ``telemetry`` block is schema-stable
    across arms: with --telemetry it reports the windowed dispatch-p99
    trajectory, a burn-rate peak against an unattainable SLO and a
    per-window bottleneck histogram; without it the same keys carry
    the false/empty/null arm so twin-run diffs never branch on shape."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "fleet_sim", os.path.join(REPO, "scripts", "fleet_sim.py"))
    fleet_sim = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fleet_sim)

    # telemetry arm: fast windows + a 0.5ms SLO no real step can meet,
    # so the burn pair fires deterministically
    monkeypatch.setattr(sys, "argv", [
        "fleet_sim.py", "--clients", "4", "--steps", "2",
        "--rate", "5.0", "--batch", "4", "--workers", "4",
        "--telemetry", "--telemetry-interval-s", "0.1",
        "--slo-ms", "0.5"])
    assert fleet_sim.main() == 0
    out = capsys.readouterr().out
    block = json.loads(out[out.index("{"):])["telemetry"]
    assert set(block) == TELEMETRY_KEYS
    assert block["enabled"] is True
    assert block["interval_s"] == 0.1
    assert block["windows"] > 0
    assert len(block["p99_ms_trajectory"]) == block["windows"]
    assert any(v is not None for v in block["p99_ms_trajectory"])
    assert block["burn_peak"] is not None and block["burn_peak"] > 1.0
    assert block["bottleneck_histogram"]
    assert set(block["bottleneck_histogram"]) <= {"queue_wait",
                                                 "compute"}
    for alert in block["slo_alerts"]:
        assert alert["state"] in ("firing", "cleared")

    # null arm: same keys, false/empty/null values
    monkeypatch.setattr(sys, "argv", [
        "fleet_sim.py", "--clients", "2", "--steps", "1",
        "--rate", "5.0", "--batch", "4"])
    assert fleet_sim.main() == 0
    out = capsys.readouterr().out
    null_arm = json.loads(out[out.index("{"):])["telemetry"]
    assert null_arm == {"enabled": False, "interval_s": None,
                        "windows": 0, "p99_ms_trajectory": [],
                        "burn_peak": None, "slo_alerts": [],
                        "bottleneck_histogram": {}}


@pytest.mark.slow
def test_bench_fleet_telemetry_role_quick():
    """bench.py --role fleet_telemetry --quick end to end: the
    telemetry-on twin stays inside the 2% steps/sec budget, the
    critical path pins the synthetic-slow middle stage in >=90% of
    warm windows, the 3-replica burn pair fires against an
    unattainable SLO, and per-replica labeled series render."""
    sys.path.insert(0, REPO)
    from bench import measure_fleet_telemetry
    r = measure_fleet_telemetry(quick=True)

    assert r["leg"] == "fleet_telemetry"
    assert r["stages"] == 3 and r["replicas"] == 3

    ov = r["telemetry_overhead"]
    assert set(ov) == {"steps_per_sec_off", "steps_per_sec_on",
                       "overhead_frac", "budget_frac"}
    assert ov["steps_per_sec_off"] > 0 and ov["steps_per_sec_on"] > 0

    attr = r["attribution"]
    assert attr["slow_party"] == "stage1"
    assert attr["windows_attributed"] > 0
    assert attr["accuracy"] >= attr["accuracy_floor"] == 0.9
    assert attr["bottleneck_histogram"].get("stage1", 0) > 0

    burn = r["slo_burn"]
    assert burn["fired"] is True
    assert burn["windows"] > 0
    assert any(a["state"] == "firing" for a in burn["alerts"])

    assert r["per_replica_labeled_series"] > 0

    # the only tolerated invalidity is steps/sec noise on a loaded
    # box; every deterministic gate above must hold regardless
    if not r["valid"]:
        assert "slower than off" in (r["invalid_reason"] or "")


@pytest.mark.slow
def test_bench_mpmd_compressed_role_quick():
    """bench.py --role mpmd_compressed --quick end to end: dense vs
    topk8 vs clapping over real HTTP loopback hop wires. Both
    compressed modes must cut hop bytes >=10x AND hold end loss inside
    the absolute-nats budget through their own wire; clapping's extras
    must be ledger-free while topk8's carry one; and the packed payload
    shapes must be dispatch-stable (zero steady-state recompiles)."""
    sys.path.insert(0, REPO)
    from bench import measure_mpmd_compressed
    r = measure_mpmd_compressed(quick=True)

    assert r["leg"] == "mpmd_compressed"
    assert r["stages"] == 3 and r["microbatches"] == 4
    for mode in ("dense", "topk8", "clapping"):
        assert r["hop_wire_bytes"][mode] > 0
    for mode in ("topk8", "clapping"):
        assert r["hop_byte_reduction"][mode] >= 10.0
        assert r["loss_parity_nats"][mode] <= r["nats_budget"]
    assert r["clapping_extras_ledger_free"] is True
    assert r["topk8_extras_carry_ledger"] is True
    assert r["steady_state_recompiles"] == 0
    assert r["valid"] is True, r["invalid_reason"]


def test_bench_composed_topology_role_quick():
    """The composed_topology leg's contract fields (composable party
    runtime): a 3-stage chain whose middle stage runs a data=2 pjit
    mesh vs the flat twin at the same per-device rows-per-microbatch
    ceiling, plus a replicated (N=2) x sharded x 3-stage run with a
    mid-run replica kill. Gates carried by the leg itself: mesh=1
    bit-identity, data=2 float parity, a strict throughput win for the
    sharded chain, zero dropped steps with >= 1 handoff across the
    kill, zero steady-state recompiles, and the stage_report mesh
    column reporting the sharded axis (MFU honestly None on CPU)."""
    sys.path.insert(0, REPO)
    from bench import measure_composed_topology

    r = measure_composed_topology(quick=True)
    assert r["leg"] == "composed_topology"
    assert r["valid"] is True, r["invalid_reason"]
    assert r["stages"] == 3
    assert r["batch_ceiling_relative"] is True
    assert "ceiling" in r["note"]  # the honesty caveat ships with the leg
    assert r["mesh"]["devices"] == 2 and r["mesh"]["data"] == 2
    # same 16-row step either way: data=2 admits double-size microbatches
    assert r["microbatches"]["data1"] == 2 * r["microbatches"]["data2"]
    assert r["steps_per_sec_data2"] > r["steps_per_sec_data1"] > 0
    assert r["speedup_data2_vs_data1"] > 1.0
    assert r["loss_mesh1_max_abs_diff"] == 0.0
    assert r["loss_data2_max_abs_diff"] <= r["parity_tol"]
    assert (r["replicated_steps_completed"]
            == r["replicated_steps_expected"])
    assert r["replica_handoffs"] >= 1
    assert r["compile_count"]["steady_state"] == 0
    rep = {row["stage"]: row for row in r["stage_report_data2"]}
    assert rep[1]["mesh"]["data"] == 2 and rep[1]["mfu"] is None
    assert rep[2]["mesh"]["data"] == 1
