"""Worker for the two-process jax.distributed smoke test.

Spawned by tests/test_distributed.py. Distributed mode: SLT_COORDINATOR /
SLT_NUM_PROCESSES / SLT_PROCESS_ID in the environment — the exact env
surface a k8s StatefulSet pod would get (distributed.py module docstring)
— plus 2 virtual CPU devices per process; joins via init_multi_host (gloo
collectives), builds the global (data x pipe) mesh with the
pipe-within-host layout, and runs fused DP steps whose gradient psum
crosses the process boundary (the DCN-analog hop). Control mode (no
SLT_* env, 4 virtual devices in one process): the same mesh shape and
computation without jax.distributed. The parent compares the printed loss
series across all three processes — replica consistency AND
single-process equivalence are both machine-checked.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from split_learning_tpu.parallel.distributed import (  # noqa: E402
    global_mesh, init_multi_host)

distributed = init_multi_host()

import jax  # noqa: E402  (backend init must follow init_multi_host)
import numpy as np  # noqa: E402

from split_learning_tpu.models import get_plan  # noqa: E402
from split_learning_tpu.runtime.fused import FusedSplitTrainer  # noqa: E402
from split_learning_tpu.utils import Config  # noqa: E402

assert jax.process_count() == (2 if distributed else 1)
devs = jax.devices()
assert len(devs) == 4, devs

# 2 hosts x 2 local devices (distributed) or 4 local devices (control);
# stages pack within a host, hosts stack on data
mesh = global_mesh(num_clients=2, num_stages=2)
if distributed:
    for row in np.asarray(mesh.devices).reshape(2, 2):
        procs = {d.process_index for d in row}
        assert len(procs) == 1, f"pipe chain crosses processes: {row}"

# identical global batch on every host (the data feeding contract)
rs = np.random.RandomState(0)
x = rs.randn(16, 28, 28, 1).astype(np.float32)
y = rs.randint(0, 10, (16,)).astype(np.int64)
cfg = Config(mode="split", batch_size=16)
trainer = FusedSplitTrainer(get_plan(mode="split"), cfg,
                            jax.random.PRNGKey(0), x, mesh=mesh)
losses = [trainer.train_step(x, y) for _ in range(8)]
assert all(np.isfinite(l) for l in losses), losses
# grads actually applied (params changed), and repeating the same batch
# converges on it (after the early overshoot this lr/data combo shows)
assert losses[1] != losses[0], losses
assert losses[-1] < losses[0], losses
tag = jax.process_index() if distributed else "control"
print("RESULT process=%s losses=%s"
      % (tag, ",".join(f"{l:.6f}" for l in losses)), flush=True)
sys.exit(0)
