"""README "Environment knobs" coverage: every ``SLT_*`` variable the
package, bench.py, or scripts/ read must appear in the README table.
The table is hand-written prose; this grep is what keeps it honest —
add a knob without documenting it and this fails with the name."""

import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KNOB = re.compile(r"SLT_[A-Z][A-Z0-9_]*")


def _source_files():
    for root in ("split_learning_tpu", "scripts"):
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(REPO, root)):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in filenames:
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)
    yield os.path.join(REPO, "bench.py")


def test_every_slt_knob_is_documented_in_readme():
    knobs = set()
    for path in _source_files():
        with open(path, encoding="utf-8") as f:
            knobs.update(KNOB.findall(f.read()))
    assert len(knobs) >= 40, sorted(knobs)  # the surface as of PR 13

    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as f:
        readme = f.read()
    documented = set(KNOB.findall(readme))
    missing = sorted(knobs - documented)
    assert not missing, (
        "SLT_* knobs read by the code but absent from the README "
        f"'Environment knobs' table: {missing}")


def test_readme_documents_no_phantom_knobs():
    """The inverse direction, looser: a knob named in the README must
    exist somewhere in the tree (tests included — some knobs are
    exercised only there), so renames can't leave stale rows behind."""
    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as f:
        documented = set(KNOB.findall(f.read()))
    tree = set()
    for path in _source_files():
        with open(path, encoding="utf-8") as f:
            tree.update(KNOB.findall(f.read()))
    for dirpath, dirnames, filenames in os.walk(os.path.join(REPO, "tests")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                with open(os.path.join(dirpath, fn), encoding="utf-8") as f:
                    tree.update(KNOB.findall(f.read()))
    phantom = sorted(documented - tree)
    assert not phantom, f"README documents knobs nothing reads: {phantom}"
