"""Flight recorder (obs/flight.py) acceptance: the zero-overhead-off
bit-identity pin, bounded ring memory under soak, the chaos postmortem
demo (duplicate served from the replay cache, visible in the merged
cross-party timeline, zero anomalies), and the watchdog-trip dump
trigger under SLT_LOCK_DEBUG=1 (subprocess — the conftest session gate
treats default-graph violations as suite bugs)."""

import importlib.util
import json
import os
import subprocess
import sys
import textwrap
import urllib.request

import jax
import numpy as np
import pytest

from split_learning_tpu.models import get_plan
from split_learning_tpu.obs import flight
from split_learning_tpu.obs import spans
from split_learning_tpu.runtime import ServerRuntime, SplitClientTrainer
from split_learning_tpu.runtime.client import FailurePolicy
from split_learning_tpu.transport import LocalTransport
from split_learning_tpu.transport.chaos import ChaosPolicy, ChaosTransport
from split_learning_tpu.transport.http import SplitHTTPServer
from split_learning_tpu.utils import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _recorder_off():
    """The global recorder must never leak between tests — the rest of
    the suite (and the off leg below) pins the recorder-off hot path."""
    flight.disable()
    yield
    flight.disable()


def _data(batch=8, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(batch, 28, 28, 1).astype(np.float32)
    y = rs.randint(0, 10, (batch,)).astype(np.int64)
    return x, y


def _train(steps=3, batch=8):
    """One seeded local split run; returns its loss series."""
    cfg = Config(mode="split", batch_size=batch)
    plan = get_plan(mode="split")
    x, y = _data(batch)
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x)
    trainer = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                 LocalTransport(server))
    try:
        return [float(trainer.train_step(x, y, i)) for i in range(steps)]
    finally:
        server.close()


# --------------------------------------------------------------------- #
# zero-overhead-off: bit identity


def test_recorder_on_leaves_loss_series_bit_identical():
    """The recorder observes; it must never perturb. The same seeded run
    with the recorder off and on produces float-identical losses, and
    the on-run actually journaled the causal taxonomy."""
    assert flight.get_recorder() is None
    losses_off = _train()
    assert flight.get_recorder() is None  # nothing armed it mid-run

    fl = flight.enable(party="proc")
    try:
        losses_on = _train()
        names = {e["name"] for e in fl.events()}
    finally:
        flight.disable()
    assert losses_on == losses_off  # bitwise: same floats, not approx
    assert {spans.FL_SEND, spans.FL_RECV, spans.FL_CLAIM_BEGIN,
            spans.FL_CLAIM_RESOLVE, spans.FL_DISPATCH,
            spans.FL_REPLY} <= names
    # every event is stamped for the cross-party merge
    for e in fl.events():
        assert e["seq"] >= 0 and e["party"] in ("client", "server", "proc")


def test_recorder_on_leaves_wire_bytes_legacy():
    """The journal is process-local: with the recorder ON the raw HTTP
    wire payloads are bit-for-bit the legacy schema — no flight fields
    travel (the tracer's pinned contract, tests/test_obs.py)."""
    from split_learning_tpu.transport import codec
    cfg = Config(mode="split", batch_size=8)
    plan = get_plan(mode="split")
    x, y = _data()
    runtime = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x)
    server = SplitHTTPServer(runtime).start()
    flight.enable(party="server")
    try:
        trainer = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                     LocalTransport(runtime))
        trainer.train_step(x, y, 0)
        acts = np.asarray(trainer._fwd(trainer.state.params,
                                       jax.numpy.asarray(x)))
        payload = codec.encode({"activations": acts, "labels": y,
                                "step": 1, "client_id": 0})
        req = urllib.request.Request(
            f"{server.url}/forward_pass", data=payload,
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(req) as resp:
            out = codec.decode(resp.read())
        assert set(out) == {"grads", "loss", "step"}
        # and the journal is served live on the debug route instead
        with urllib.request.urlopen(f"{server.url}/debug/flight") as resp:
            doc = json.loads(resp.read())
        assert doc["kind"] == "slt-flight-dump"
        assert any(e["name"] == spans.FL_RECV for e in doc["events"])
    finally:
        flight.disable()
        server.stop()


def test_debug_flight_route_404_when_off():
    cfg = Config(mode="split", batch_size=8)
    plan = get_plan(mode="split")
    x, _ = _data()
    runtime = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x)
    server = SplitHTTPServer(runtime).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{server.url}/debug/flight")
        assert ei.value.code == 404
    finally:
        server.stop()


# --------------------------------------------------------------------- #
# bounded memory


def test_ring_stays_bounded_under_soak():
    fl = flight.enable(party="proc", capacity=64)
    try:
        for i in range(1000):
            fl.record(spans.FL_ADMIT, step=i, client_id=0, tenant=0)
        events = fl.events()
        assert len(events) == 64
        assert events[-1]["step"] == 999  # newest survive, oldest drop
        dump = fl.dump(reason="soak")
        assert dump["dropped"] == 1000 - 64
        # a real run on top keeps the same bound
        _train(steps=2, batch=4)
        assert len(fl.events()) == 64
    finally:
        flight.disable()


def test_dump_json_roundtrip(tmp_path):
    fl = flight.enable(party="proc", capacity=8)
    try:
        fl.record(spans.FL_BREAKER, step=0, client_id=1,
                  state="open", reason="probe")
        out = fl.dump_json(str(tmp_path / "d.json"), reason="manual")
        with open(out) as f:
            doc = json.load(f)
        assert doc["version"] == 1 and doc["reason"] == "manual"
        assert doc["events"][0]["fields"] == {"state": "open",
                                              "reason": "probe"}
    finally:
        flight.disable()


# --------------------------------------------------------------------- #
# the postmortem demo: chaos duplicates, exactly-once, zero anomalies


def _load_postmortem():
    spec = importlib.util.spec_from_file_location(
        "postmortem", os.path.join(REPO, "scripts", "postmortem.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_postmortem_shows_duplicate_served_from_replay(tmp_path, capsys):
    """The acceptance demo: a chaos run (drop_resp + dup) with the
    recorder on produces client+server journals whose postmortem merge
    shows the duplicate arriving, losing the replay claim (owner=False),
    and being served from the cache — with zero ordering anomalies."""
    steps = 6
    cfg = Config(mode="split", batch_size=4)
    plan = get_plan(mode="split")
    x, y = _data(batch=4)
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x,
                           strict_steps=True)
    policy = ChaosPolicy("drop_resp=0.3,dup=0.3", seed=3)
    transport = ChaosTransport(LocalTransport(server), policy)
    trainer = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                 transport,
                                 failure_policy=FailurePolicy.RETRY,
                                 max_retries=5)
    fl = flight.enable(party="proc")
    try:
        losses = [float(trainer.train_step(x, y, i)) for i in range(steps)]
        events = fl.events()
        base = fl.dump(reason="exit")
    finally:
        flight.disable()
        server.close()
    assert len(losses) == steps  # exactly-once: every step trained once
    assert sum(policy.injected.values()) > 0

    # split the single-process journal by party into the two dump files
    # a real two-party deployment would write
    paths = []
    for party in ("client", "server"):
        doc = dict(base, party=party,
                   events=[e for e in events if e["party"] == party])
        assert doc["events"], f"no {party}-party events journaled"
        p = tmp_path / f"{party}.flight.json"
        p.write_text(json.dumps(doc))
        paths.append(str(p))

    pm = _load_postmortem()
    dumps = [pm.load_dump(p) for p in paths]
    rep = pm.summarize(dumps)
    assert rep["anomalies"] == []
    assert rep["chaos"].get("drop_resp", 0) + rep["chaos"].get("dup", 0) > 0
    # the duplicate's fate: it waited on (or replay-hit) a claim it did
    # not own instead of dispatching a second time
    dup_rows = rep["duplicates_served"]
    assert dup_rows, "chaos injected duplicates but none were journaled"
    assert any(r["claim_wait"] + r["replay_hit"] >= 1 for r in dup_rows)

    # the CLI face renders and exits 0 (no anomalies even under --strict)
    assert pm.main(paths + ["--strict"]) == 0
    out = capsys.readouterr().out
    assert "anomalies: none" in out


def test_postmortem_flags_reply_before_admit(tmp_path):
    """Anomaly detection proper: a synthetic journal whose reply count
    outruns its admits must be flagged (the detector the chaos demo
    proves stays quiet on a healthy run)."""
    fl = flight.FlightRecorder(party="server")
    fl.record(spans.FL_ADMIT, step=0, client_id=0, tenant=0)
    fl.record(spans.FL_REPLY, step=0, client_id=0, op="forward_pass")
    fl.record(spans.FL_REPLY, step=1, client_id=0, op="forward_pass")
    p = tmp_path / "bad.flight.json"
    p.write_text(json.dumps(fl.dump(reason="exit")))
    pm = _load_postmortem()
    rep = pm.summarize([pm.load_dump(str(p))])
    kinds = {a["kind"] for a in rep["anomalies"]}
    assert "reply_before_admit" in kinds
    assert pm.main([str(p), "--strict"]) == 1


# --------------------------------------------------------------------- #
# watchdog-trip dump (trigger #1), in a subprocess so the intentional
# inversion never reaches this session's default-graph gate


def test_watchdog_trip_dumps_flight_journal(tmp_path):
    dump_path = tmp_path / "trip.flight.json"
    script = textwrap.dedent("""
        from split_learning_tpu.obs import flight, locks
        flight.maybe_enable_from_env()
        assert flight.enabled() and locks.enabled()
        a = locks.make_lock("a", reentrant=False)
        b = locks.make_lock("b", reentrant=False)
        with a:
            with b:
                pass
        with b:
            with a:   # a->b then b->a: the inversion the watchdog trips on
                pass
        assert locks.default_graph().violations
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SLT_LOCK_DEBUG="1", SLT_FLIGHT=str(dump_path))
    proc = subprocess.run([sys.executable, "-c", script], cwd=REPO,
                          env=env, capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    with open(dump_path) as f:
        doc = json.load(f)
    assert doc["kind"] == "slt-flight-dump"
    assert doc["reason"] == "watchdog:lock"
    trips = [e for e in doc["events"]
             if e["name"] == spans.FL_WATCHDOG_TRIP]
    assert trips and trips[0]["fields"]["source"] == "lock"
    assert "lock-order" in trips[0]["fields"]["message"] or \
        "inversion" in trips[0]["fields"]["message"]
