"""FLOPs/MFU accounting (utils/flops.py) — the utilization terms the
round-1 bench lacked (VERDICT weak #2).

Hand-counted ground truth for the split CNN (B = batch):
- conv1: out [B,26,26,32], kernel 3x3x1   -> 2 * B*26*26*32 * 9*1  FLOPs
- conv2: out [B,24,24,64], kernel 3x3x32  -> 2 * B*24*24*64 * 9*32 FLOPs
- fc:    [B,9216] @ [9216,10]             -> 2 * B*9216*10        FLOPs
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from split_learning_tpu.core.losses import cross_entropy
from split_learning_tpu.models import get_plan
from split_learning_tpu.utils.flops import (
    device_peak_flops, jaxpr_matmul_flops, mfu)

B = 8


def _fwd_flops_by_hand(b: int) -> float:
    conv1 = 2 * b * 26 * 26 * 32 * 9 * 1
    conv2 = 2 * b * 24 * 24 * 64 * 9 * 32
    fc = 2 * b * 9216 * 10
    return float(conv1 + conv2 + fc)


@pytest.fixture(scope="module")
def cnn():
    plan = get_plan(mode="split")
    x = jnp.zeros((B, 28, 28, 1), jnp.float32)
    y = jnp.zeros((B,), jnp.int32)
    params = plan.init(jax.random.PRNGKey(0), x)
    return plan, params, x, y


def test_forward_flops_match_hand_count(cnn):
    plan, params, x, _ = cnn
    got = jaxpr_matmul_flops(lambda p, xx: plan.apply(p, xx), params, x)
    assert got == _fwd_flops_by_hand(B)


def test_grad_step_flops_about_3x_forward(cnn):
    """The differentiated graph carries the transposed convs/dots; the
    classic estimate is bwd ~ 2x fwd, so fwd+bwd in [2x, 4x] fwd."""
    plan, params, x, y = cnn

    def loss_fn(p, xx, yy):
        return cross_entropy(plan.apply(p, xx), yy)

    fwd = _fwd_flops_by_hand(B)
    got = jaxpr_matmul_flops(jax.value_and_grad(loss_fn), params, x, y)
    assert 2.0 * fwd <= got <= 4.0 * fwd


def test_scan_multiplies_by_trip_count(cnn):
    plan, params, x, _ = cnn
    T = 5

    def scanned(p, xs):
        def body(carry, xx):
            return carry, plan.apply(p, xx)
        return jax.lax.scan(body, 0, xs)

    xs = jnp.zeros((T,) + x.shape, x.dtype)
    got = jaxpr_matmul_flops(scanned, params, xs)
    assert got == T * _fwd_flops_by_hand(B)


@pytest.mark.slow
def test_resnet_flops_positive_and_batch_linear():
    plan = get_plan(model="resnet18", mode="split")
    x1 = jnp.zeros((2, 32, 32, 3), jnp.float32)
    x2 = jnp.zeros((4, 32, 32, 3), jnp.float32)
    params = plan.init(jax.random.PRNGKey(0), x1)
    f1 = jaxpr_matmul_flops(lambda p, xx: plan.apply(p, xx), params, x1)
    f2 = jaxpr_matmul_flops(lambda p, xx: plan.apply(p, xx), params, x2)
    assert f1 > 1e6  # ResNet-18 on 32x32 is tens of MFLOPs per image
    assert f2 == pytest.approx(2 * f1)


def test_remat_does_not_double_count(cnn):
    """jax.checkpoint wraps the forward in a remat sub-jaxpr; the plain
    forward count must not change."""
    from split_learning_tpu.core.stage import remat_plan
    plan, _, x, _ = cnn
    rplan = remat_plan(plan)
    params = rplan.init(jax.random.PRNGKey(0), x)
    got = jaxpr_matmul_flops(lambda p, xx: rplan.apply(p, xx), params, x)
    assert got == _fwd_flops_by_hand(B)


def test_peak_and_mfu_semantics():
    # CPU devices have no published MXU peak -> None -> mfu None
    assert device_peak_flops(jax.devices("cpu")[0]) is None
    assert mfu(1e12, None) is None
    assert mfu(98.5e12, 197e12) == pytest.approx(0.5)
