"""The committed north-star parity artifact (BASELINE.json: "same loss
curve") — round-2 VERDICT missing #2.

``artifacts/parity_mnist_split.jsonl`` holds the reference's full 3-epoch
workload (938 steps/epoch x 3, SGD lr=0.01, batch 64 — the hyperparameters
of ``/root/reference/src/client_part.py:17,98,107``) trained four ways (the fourth, http_pipelined, checks convergence only):
monolithic (ground truth), fused (the TpuTransport path), and HTTP
loopback (the reference topology). This test does not trust the artifact's
own summary record: it recomputes every pairwise diff from the committed
loss series. Regenerate with ``scripts/make_parity_artifact.py``.
"""

import json
import os

import numpy as np
import pytest

ARTIFACT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "artifacts", "parity_mnist_split.jsonl")

# measured headroom: fused-vs-mono is exactly 0.0 on CPU (same math, same
# XLA); http adds one codec f32 round trip -> one-ULP diffs (2.4e-7
# observed). 1e-4 over 2,814 chained SGD steps still pins "same curve"
# while absorbing BLAS/XLA version drift on regeneration.
TOL = 1e-4


@pytest.fixture(scope="module")
def artifact():
    assert os.path.exists(ARTIFACT), (
        f"missing {ARTIFACT}; run scripts/make_parity_artifact.py")
    with open(ARTIFACT) as f:
        records = [json.loads(line) for line in f if line.strip()]
    meta = next(r for r in records if r["kind"] == "meta")
    curves = {r["variant"]: r for r in records if r["kind"] == "curve"}
    return meta, curves


def test_artifact_covers_reference_workload(artifact):
    meta, curves = artifact
    # the reference's exact training shape, src/client_part.py:98,107
    assert meta["batch"] == 64 and meta["lr"] == 0.01 and meta["epochs"] == 3
    assert meta["n_train"] == 60_000
    assert meta["total_steps"] == 2_814
    if meta["dataset"] != "mnist":
        # synthetic must be provably forced: the artifact carries the
        # real-data download attempt and its error (VERDICT r3 missing #1)
        attempt = meta["attempted_real_data"]
        assert attempt["attempted"] is True and attempt["error"]
    for name in ("monolithic", "fused", "http"):
        assert name in curves, f"variant {name} missing"
        assert len(curves[name]["losses"]) == meta["total_steps"]


def test_split_curves_match_monolithic(artifact):
    _, curves = artifact
    mono = np.asarray(curves["monolithic"]["losses"])
    for name in ("fused", "http"):
        diff = np.max(np.abs(np.asarray(curves[name]["losses"]) - mono))
        assert diff <= TOL, f"{name} vs monolithic: max diff {diff}"


def test_curves_show_learning(artifact):
    """Parity between three flat lines would prove nothing: the curve must
    actually descend across the run."""
    _, curves = artifact
    for name, rec in curves.items():
        losses = np.asarray(rec["losses"])
        head, tail = losses[:100].mean(), losses[-100:].mean()
        assert tail < 0.1 * head, (name, head, tail)


def test_pipelined_variant_converges_to_monolithic(artifact):
    """The depth-4 bounded-staleness curve is NOT expected to match
    monolithic step-for-step (delay < 4); the claim it must support is
    convergence: over the full 2,814-step workload it ends where the
    exact curve ends."""
    _, curves = artifact
    if "http_pipelined" not in curves:
        pytest.skip("artifact generated without the http_pipelined variant")
    piped = np.asarray(curves["http_pipelined"]["losses"])
    mono = np.asarray(curves["monolithic"]["losses"])
    assert len(piped) == len(mono)
    assert piped[-100:].mean() < 2.0 * max(mono[-100:].mean(), 1e-4)


def test_tpu_leg_matches_monolithic_when_present(artifact):
    """North-star closure (BASELINE.json: "same loss curve" on TPU): when
    the artifact carries a fused curve produced on the chip
    (``make_parity_artifact.py --variant fused`` on a TPU backend, run by
    scripts/tpu_window_runner.py), it must track the CPU monolithic
    ground truth. TPU f32 conv accumulation differs from CPU at the ULP
    level and 2,814 chained SGD steps amplify it, so the claim is staged:
    near-exact early (before divergence can compound) and same
    convergence endpoint late."""
    _, curves = artifact
    if "fused_tpu" not in curves:
        pytest.skip("artifact has no on-device fused curve yet")
    tpu = np.asarray(curves["fused_tpu"]["losses"])
    mono = np.asarray(curves["monolithic"]["losses"])
    assert len(tpu) == len(mono)
    # Measured on the chip (2026-07-31 window): max |diff| over the
    # full 2,814-step run is 7.8e-3, hit at step 6 where loss ~6 (0.2%
    # relative — TPU conv accumulation order); tail means agree to 4
    # significant figures. Bound the whole curve at 2e-2.
    assert np.max(np.abs(tpu - mono)) <= 2e-2
    assert tpu[-100:].mean() < 1.1 * max(mono[-100:].mean(), 1e-4)


def test_http_leg_measures_roundtrip(artifact):
    """The artifact also records the measured per-step cut-layer exchange
    cost of the reference topology (vs which the fused path's whole step
    is ~0.2 ms, BASELINE.md)."""
    _, curves = artifact
    p50 = curves["http"]["roundtrip_p50_ms"]
    assert p50 > 1.0, "loopback round trip of 2x5.28 MiB can't be free"
