"""Cross-framework parity: the JAX split CNN vs a reference-style torch
implementation (scripts/make_torch_parity_artifact.py).

The reference's acceptance criterion is its torch loss curve
(``/root/reference/src/client_part.py:107``, curve eyeballed per
``README.md:105-107``). The committed ``parity_mnist_split.jsonl``
establishes split ≡ monolithic within this framework; these tests pin
the remaining step — this framework ≡ the reference's own stack — by
(a) checking the weight-export forward equivalence live, (b) training
both stacks for a few steps from identical init/data and comparing
per-step losses, and (c) asserting the committed full-workload artifact.
"""

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

torch = pytest.importorskip("torch")

from make_torch_parity_artifact import (  # noqa: E402
    build_torch_split, compare, jax_init_params, run_torch)

ARTIFACT = os.path.join(REPO, "artifacts", "parity_vs_torch.jsonl")


def _synthetic(n=512):
    from split_learning_tpu.data.datasets import synthetic
    ds = synthetic("mnist", n_train=n, n_test=64, seed=0)
    return ds.train.x, ds.train.y


def test_weight_export_forward_equivalence():
    """flax NHWC params exported into torch NCHW layout must produce the
    same logits — this is the mapping the whole artifact rests on (conv
    HWIO->OIHW, fc rows remapped HWC->CHW)."""
    import jax.numpy as jnp

    from split_learning_tpu.models import get_plan

    params = jax_init_params()
    part_a, part_b = build_torch_split(params)
    x, _ = _synthetic(8)
    x = x[:8]

    plan = get_plan(mode="split")
    jax_logits = np.asarray(plan.apply(params, jnp.asarray(x)))
    with torch.no_grad():
        t_logits = part_b(part_a(
            torch.from_numpy(x.transpose(0, 3, 1, 2).copy()))).numpy()
    np.testing.assert_allclose(jax_logits, t_logits, atol=2e-5)


@pytest.mark.slow
def test_short_training_curves_track():
    """Same init, same batch order, same SGD: torch and JAX per-step
    losses must agree to f32 cross-library conv drift over 12 steps.
    (The committed artifact extends this to the full 2,814 steps.)"""
    import jax
    import jax.numpy as jnp

    from split_learning_tpu.core import cross_entropy
    from split_learning_tpu.models import get_plan
    from split_learning_tpu.runtime import apply_grads, make_state, sgd

    x, y = _synthetic(12 * 64)
    steps = 12

    torch_losses = run_torch(x, y, steps_limit=steps)

    plan = get_plan(mode="split")
    params = plan.init(jax.random.PRNGKey(42), jnp.asarray(x[:64]))
    tx = sgd(0.01)
    state = make_state(tuple(params), tx)

    @jax.jit
    def step(state, xb, yb):
        loss, grads = jax.value_and_grad(
            lambda p: cross_entropy(plan.apply(p, xb), yb))(state.params)
        return apply_grads(tx, state, grads), loss

    from make_torch_parity_artifact import epoch_batches
    jax_losses = []
    for xb, yb in epoch_batches(x, y, 0):
        state, loss = step(state, jnp.asarray(xb), jnp.asarray(yb))
        jax_losses.append(float(loss))
        if len(jax_losses) >= steps:
            break

    diffs = [abs(a - b) for a, b in zip(jax_losses, torch_losses)]
    assert max(diffs) < 1e-4, (jax_losses, torch_losses)


def test_committed_artifact_full_workload():
    """The committed artifact must cover the reference's complete
    3-epoch workload with curve agreement at the numerics floor (the
    stored JAX curve rounds to 4 decimals, so the floor is ~5e-5)."""
    assert os.path.exists(ARTIFACT), (
        "run scripts/make_torch_parity_artifact.py")
    records = [json.loads(l) for l in open(ARTIFACT)]
    by_kind = {}
    for r in records:
        by_kind.setdefault(r["kind"], []).append(r)
    meta = by_kind["meta"][0]
    summary = by_kind["summary"][0]
    variants = {c["variant"] for c in by_kind["curve"]}
    assert variants == {"torch_reference", "jax_monolithic"}

    assert summary["steps_compared"] == 2814  # 938 x 3 epochs
    assert summary["step0_abs_diff"] < 1e-5   # identical init, no updates
    assert summary["max_abs_diff_first_100"] < 1e-4
    assert summary["mean_abs_diff"] < 1e-4
    # the synthetic fallback must be provably forced, not chosen
    if meta["dataset"] == "mnist-synthetic":
        assert meta["attempted_real_data"]["attempted"] is True
        assert meta["attempted_real_data"]["error"]

    # the recomputed summary from the stored curves must match the
    # stored summary (the artifact is internally consistent)
    curves = {c["variant"]: c["losses"] for c in by_kind["curve"]}
    redo = compare(curves["jax_monolithic"], curves["torch_reference"])
    assert redo["mean_abs_diff"] == pytest.approx(
        summary["mean_abs_diff"], rel=1e-9)


@pytest.mark.slow
def test_adamw_curves_track_torch():
    """Cross-framework optimizer parity for the round-4 factory: the
    same init/data/batch order under make_tx(adamw + weight decay) must
    track torch.optim.AdamW step for step — optax and torch share the
    decoupled-decay formulation (update = m_hat/(sqrt(v_hat)+eps) +
    wd*param, scaled by lr), so the curves may differ only by f32
    cross-library conv drift, which adam's sqrt(v)-normalization
    amplifies only mildly over a short run."""
    import jax
    import jax.numpy as jnp

    from split_learning_tpu.core import cross_entropy
    from split_learning_tpu.models import get_plan
    from split_learning_tpu.runtime import apply_grads, make_state
    from split_learning_tpu.runtime.state import make_tx
    from split_learning_tpu.utils import Config

    from make_torch_parity_artifact import epoch_batches

    lr, wd, steps = 1e-3, 0.01, 10
    x, y = _synthetic(steps * 64)

    # torch side: one AdamW across both parties (== one optax tx over
    # the param tuple), through the same run_torch loop the artifact
    # generator uses
    torch_losses = run_torch(
        x, y, steps_limit=steps,
        opt_factory=lambda a, b: [torch.optim.AdamW(
            list(a.parameters()) + list(b.parameters()),
            lr=lr, weight_decay=wd)])

    plan = get_plan(mode="split")
    params = plan.init(jax.random.PRNGKey(42), jnp.asarray(x[:64]))
    tx = make_tx(Config(optimizer="adamw", lr=lr, weight_decay=wd))
    state = make_state(tuple(params), tx)

    @jax.jit
    def step(state, xb, yb):
        loss, grads = jax.value_and_grad(
            lambda p: cross_entropy(plan.apply(p, xb), yb))(state.params)
        return apply_grads(tx, state, grads), loss

    jax_losses = []
    for xb, yb in epoch_batches(x, y, 0):
        state, loss = step(state, jnp.asarray(xb), jnp.asarray(yb))
        jax_losses.append(float(loss))
        if len(jax_losses) >= steps:
            break

    diffs = [abs(a - b) for a, b in zip(jax_losses, torch_losses)]
    assert max(diffs) < 5e-4, (jax_losses, torch_losses)
