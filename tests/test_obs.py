"""Observability subsystem (obs/): per-step tracing, latency histograms,
Prometheus /metrics, Chrome-trace export, and the zero-overhead-off
contract across LocalTransport, HttpTransport, and the coalescer."""

import json
import threading
import urllib.request

import jax
import numpy as np
import pytest

from split_learning_tpu import obs
from split_learning_tpu.models import get_plan
from split_learning_tpu.obs.metrics import (
    Histogram, Registry, render_prometheus)
from split_learning_tpu.runtime import ServerRuntime, SplitClientTrainer
from split_learning_tpu.runtime.multi_client import MultiClientSplitRunner
from split_learning_tpu.transport import LocalTransport
from split_learning_tpu.transport.http import HttpTransport, SplitHTTPServer
from split_learning_tpu.utils import Config
from split_learning_tpu.utils.profiling import PhaseProfiler


@pytest.fixture(autouse=True)
def _tracer_off():
    """The global tracer must never leak between tests (the rest of the
    suite pins the untraced wire format)."""
    obs.disable()
    yield
    obs.disable()


def _data(batch=8, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(batch, 28, 28, 1).astype(np.float32)
    y = rs.randint(0, 10, (batch,)).astype(np.int64)
    return x, y


# --------------------------------------------------------------------- #
# histograms + Prometheus text


def test_histogram_bucket_monotonicity():
    h = Histogram()
    values = (0.00005, 0.0003, 0.003, 0.02, 0.7, 42.0)
    for v in values:
        h.observe(v)
    snap = h.snapshot()
    cum = snap["cumulative"]
    assert len(cum) == len(snap["buckets"]) + 1  # +Inf slot
    assert all(a <= b for a, b in zip(cum, cum[1:]))
    assert cum[-1] == snap["count"] == len(values)
    assert snap["sum"] == pytest.approx(sum(values))
    # a value beyond the last bound lands only in +Inf
    assert cum[-1] - cum[-2] == 1  # the 42.0 observation


def test_histogram_rejects_bad_buckets():
    with pytest.raises(ValueError):
        Histogram(buckets=(0.1, 0.1, 0.2))
    with pytest.raises(ValueError):
        Histogram(buckets=())


def test_render_prometheus_parses_as_exposition_text():
    reg = Registry()
    for v in (0.001, 0.02, 0.3):
        reg.observe("dispatch", v)
    reg.observe("queue_wait", 0.004)
    reg.incr("split_steps_total", 3)
    reg.set_gauge("acked_step", 2.0)
    text = render_prometheus(reg.snapshot())
    assert text.endswith("\n")
    seen = set()
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        # every sample line is "name[{labels}] value" with a float value
        name, val = ln.rsplit(" ", 1)
        float(val)
        seen.add(name.split("{")[0])
    assert {"slt_dispatch_seconds_bucket", "slt_dispatch_seconds_sum",
            "slt_dispatch_seconds_count", "slt_queue_wait_seconds_bucket",
            "slt_phase_fraction", "slt_split_steps_total",
            "slt_acked_step"} <= seen
    # cumulative bucket counts are monotone in exposition order and the
    # +Inf bucket equals _count
    cum = [float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
           if ln.startswith("slt_dispatch_seconds_bucket")]
    assert cum == sorted(cum)
    assert 'slt_dispatch_seconds_bucket{le="+Inf"} 3' in text
    assert "slt_dispatch_seconds_count 3" in text


# --------------------------------------------------------------------- #
# trace-ID propagation: LocalTransport (same thread)


def test_trace_id_propagates_through_local_transport():
    cfg = Config(mode="split", batch_size=8)
    plan = get_plan(mode="split")
    x, y = _data()
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x)
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server))
    tr = obs.enable()
    try:
        for i in range(3):
            client.train_step(x, y, i)
    finally:
        obs.disable()
    spans = tr.spans()
    names = {s["name"] for s in spans}
    assert {"client_fwd", "encode", "wire", "transport", "client_bwd",
            "opt_apply", "step_total", "queue_wait", "dispatch"} <= names
    # every span of one step carries the SAME trace id, client and
    # server parties both
    by_tid = {}
    for s in spans:
        assert s["trace_id"], f"span {s['name']} lost its trace id"
        by_tid.setdefault(s["trace_id"], set()).add(
            (s["name"], s["party"]))
    assert len(by_tid) == 3  # one trace per step
    for group in by_tid.values():
        assert ("client_fwd", "client") in group
        assert ("queue_wait", "server") in group
        assert ("dispatch", "server") in group
    # the transport span fully contains its encode + wire sub-spans
    summary = tr.phase_summary()
    assert summary["transport"]["total_s"] >= (
        summary["encode"]["total_s"] + summary["wire"]["total_s"]) * 0.99
    # spans aggregate into the tracer's registry histograms
    snap = tr.registry.snapshot()
    assert {"queue_wait", "dispatch", "transport"} <= set(snap["histograms"])


def test_tracing_off_leaves_transport_stats_untouched():
    """Zero-overhead-off: with the tracer off (the default) no span
    counters appear anywhere — the hot path is the untraced one."""
    cfg = Config(mode="split", batch_size=8)
    plan = get_plan(mode="split")
    x, y = _data()
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x)
    transport = LocalTransport(server)
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0), transport)
    for i in range(2):
        client.train_step(x, y, i)
    assert not any(k.startswith("span_") for k in transport.stats.counters)
    # and the server-side registry stayed empty
    assert server.metrics()["histograms"] == {}


# --------------------------------------------------------------------- #
# trace-ID propagation: HttpTransport + GET /metrics over the wire


def test_http_transport_propagates_spans_and_serves_metrics():
    cfg = Config(mode="split", batch_size=8)
    plan = get_plan(mode="split")
    x, y = _data()
    runtime = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x)
    server = SplitHTTPServer(runtime).start()
    transport = HttpTransport(server.url)
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0), transport)
    tr = obs.enable()
    try:
        for i in range(3):
            client.train_step(x, y, i)
        with urllib.request.urlopen(f"{server.url}/metrics") as resp:
            assert resp.status == 200
            assert "version=0.0.4" in resp.headers.get("Content-Type", "")
            text = resp.read().decode()
    finally:
        obs.disable()
        transport.close()
        server.stop()
    # client side saw the full taxonomy, server spans folded back via
    # the response payload
    names = {s["name"] for s in tr.spans()}
    assert {"client_fwd", "encode", "wire", "transport", "queue_wait",
            "dispatch", "step_total"} <= names
    counters = transport.stats.counters
    for k in ("span_encode_s", "span_wire_s", "span_queue_wait_s",
              "span_dispatch_s"):
        assert counters.get(k, 0.0) > 0.0
        assert counters[k.replace("_s", "_n")] == 3
    # the scraped exposition carries the server-party histograms
    assert "slt_queue_wait_seconds_bucket" in text
    assert "slt_dispatch_seconds_bucket" in text
    assert "slt_split_steps_total 3" in text
    for ln in text.splitlines():
        if ln and not ln.startswith("#"):
            float(ln.rsplit(" ", 1)[1])  # parseable exposition


def test_http_payload_unchanged_when_tracing_off():
    """The wire format with tracing off is bit-for-bit the untraced one:
    no trace_id in the request, no server_spans in the response."""
    from split_learning_tpu.transport import codec
    cfg = Config(mode="split", batch_size=8)
    plan = get_plan(mode="split")
    x, y = _data()
    runtime = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x)
    server = SplitHTTPServer(runtime).start()
    try:
        # do one normal (untraced) step to initialize, then speak the
        # raw wire protocol for the next step and inspect both payloads
        transport = HttpTransport(server.url)
        trainer = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                     transport)
        trainer.train_step(x, y, 0)
        acts = np.asarray(trainer._fwd(trainer.state.params,
                                       jax.numpy.asarray(x)))
        payload = codec.encode({"activations": acts, "labels": y,
                                "step": 1, "client_id": 0})
        req = urllib.request.Request(
            f"{server.url}/forward_pass", data=payload,
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(req) as resp:
            out = codec.decode(resp.read())
        assert set(out) == {"grads", "loss", "step"}  # no server_spans
        transport.close()
    finally:
        server.stop()


# --------------------------------------------------------------------- #
# coalescer queue-wait spans under a concurrent burst


def test_coalescer_records_queue_wait_spans_under_burst():
    n_clients, rounds = 3, 3
    plan = get_plan(mode="split")
    cfg = Config(mode="split", batch_size=4, num_clients=n_clients)
    rs = np.random.RandomState(0)
    x = rs.randn(rounds, n_clients, 4, 28, 28, 1).astype(np.float32)
    y = rs.randint(0, 10, (rounds, n_clients, 4)).astype(np.int64)
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x[0, 0],
                           coalesce_max=n_clients, coalesce_window_ms=20.0)
    runner = MultiClientSplitRunner(
        plan, cfg, jax.random.PRNGKey(1),
        lambda i: LocalTransport(server),
        num_clients=n_clients, concurrent=True)
    tr = obs.enable()
    try:
        for r in range(rounds):
            runner.train_round(list(zip(x[r], y[r])))
    finally:
        obs.disable()
        runner.close()
        server.close()
    qw = [s for s in tr.spans() if s["name"] == "queue_wait"]
    assert len(qw) == rounds * n_clients
    # enqueue -> group pickup includes the coalescer window wait, and
    # each request keeps its own client's trace id
    assert all(s["party"] == "server" for s in qw)
    assert all(s["trace_id"] for s in qw)
    client_ids = {s["tid"] for s in qw}
    assert client_ids == set(range(n_clients))
    # the window wait is real time: a full group closes on arrival of
    # the last member, so SOME request waited a measurable while
    assert max(s["duration"] for s in qw) > 0.0
    # server metrics picked the spans up as histograms
    snap = server.metrics()
    assert snap["histograms"]["queue_wait"]["count"] == rounds * n_clients
    assert snap["counters"]["split_steps_total"] == rounds * n_clients
    assert snap["counters"]["coalesce_groups_flushed"] >= rounds


# --------------------------------------------------------------------- #
# Chrome export + trace_report.py agreement with PhaseProfiler


def _load_trace_report():
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "trace_report.py")
    spec = importlib.util.spec_from_file_location("trace_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_chrome_export_and_trace_report_reproduce_fraction(tmp_path):
    cfg = Config(mode="split", batch_size=8)
    plan = get_plan(mode="split")
    x, y = _data()
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x)
    prof = PhaseProfiler()
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server), profiler=prof)
    tr = obs.enable()
    try:
        for i in range(4):
            client.train_step(x, y, i)
    finally:
        obs.disable()
    path = tr.export_chrome(str(tmp_path / "trace.json"))

    # the export is a valid Chrome trace: whole-file JSON, complete
    # events with µs timestamps, per-party process metadata
    events = json.load(open(path))
    metas = [e for e in events if e.get("ph") == "M"]
    assert {m["args"]["name"] for m in metas} == {"slt-client", "slt-server"}
    xs = [e for e in events if e.get("ph") == "X"]
    assert xs and all(e["dur"] >= 0 and e["ts"] >= 0 for e in xs)
    assert {e["pid"] for e in xs} == {1, 2}

    # ...and line-parseable (the tolerant path trace_report also takes)
    report = _load_trace_report()
    lines_events = report.load_events(path)
    assert len(lines_events) == len(events)

    rep = report.summarize(lines_events)
    # the report's transport fraction reproduces both the tracer's and
    # the PhaseProfiler's view of the same run
    assert rep["transport_fraction"] == pytest.approx(
        tr.fraction("transport"), abs=1e-9)
    assert rep["transport_fraction"] == pytest.approx(
        prof.fraction("transport"), abs=0.1)
    # acceptance gate: per-step client spans sum to within 10% of the
    # measured step_total wall clock
    assert rep["steps_with_wall_clock"] == 4
    assert 0.9 <= rep["span_sum_over_wall_clock"] <= 1.01
    # the rendered table mentions every phase
    text = report.render(rep)
    for name in ("client_fwd", "transport", "queue_wait", "dispatch"):
        assert name in text


def test_trace_report_tolerates_truncated_file(tmp_path):
    """A live/crashed export (no closing bracket, torn last line) still
    yields every complete event."""
    tr = obs.enable()
    try:
        t0 = 0.0
        for i in range(5):
            tr.record("client_fwd", t0 + i, 0.01, trace_id=f"t{i}")
    finally:
        obs.disable()
    full = tr.export_chrome(str(tmp_path / "full.json"))
    content = open(full).read()
    torn = tmp_path / "torn.json"
    torn.write_text(content.rsplit("\n", 3)[0] + '\n{"name": "client_')
    report = _load_trace_report()
    events = report.load_events(str(torn))
    assert len(events) >= 5  # metadata + all complete span lines


def test_trace_report_compile_summary(tmp_path):
    """xla_compile spans (the dispatch watchdog's trace export) get
    their own section: count/total/max plus the steady-state count
    (args.step >= 2 — a recompile storm). Tolerant: missing or
    non-numeric step fields count as non-steady, and a trace without
    compile events renders with no compile section at all."""
    report = _load_trace_report()
    lines = [
        '{"ph": "X", "name": "client_fwd", "ts": 0, "dur": 1000, '
        '"pid": 1, "tid": 1}',
        '{"ph": "X", "name": "xla_compile", "ts": 0, "dur": 250000, '
        '"pid": 2, "tid": 1, "args": {"step": 0}}',
        '{"ph": "X", "name": "xla_compile", "ts": 1, "dur": 50000, '
        '"pid": 2, "tid": 1, "args": {"step": 3}}',
        '{"ph": "X", "name": "xla_compile", "ts": 2, "dur": 10000, '
        '"pid": 2, "tid": 1}',
        '{"ph": "X", "name": "xla_compile", "ts": 3, "dur": 10000, '
        '"pid": 2, "tid": 1, "args": {"step": "?"}}',
        '{"ph": "X", "name": "xla_comp',  # torn tail of a live file
    ]
    torn = tmp_path / "live.json"
    torn.write_text("[\n" + ",\n".join(lines))
    events = report.load_events(str(torn))
    rep = report.summarize(events)
    comp = rep["compile"]
    assert comp["count"] == 4
    assert comp["total_s"] == pytest.approx(0.32)
    assert comp["max_ms"] == pytest.approx(250.0)
    assert comp["steady_state_count"] == 1
    text = report.render(rep)
    assert "xla compiles: 4" in text and "recompile storm" in text
    rep0 = report.summarize(
        [e for e in events if e.get("name") != "xla_compile"])
    assert rep0["compile"]["count"] == 0
    assert "xla compiles" not in report.render(rep0)


def test_trace_report_schedules_section(tmp_path):
    """--schedules summarizes an slt-check explorer report: per-scenario
    schedules/pruned/pruning-ratio/max-preemption rows, skipped
    scenarios marked, and each violation rendered with its replayable
    schedule id."""
    report = _load_trace_report()
    check = {
        "total_schedules": 110,
        "scenarios": {
            "replay_dup_storm": {
                "schedules": 100, "pruned": 50, "pruning_ratio": 1 / 3,
                "exhausted": False, "max_preemptions": 3,
                "max_transitions": 80, "invariants": ["no_errors"],
                "violations": [], "sample_fingerprints": {}},
            "toy_broken": {
                "schedules": 10, "pruned": 0, "pruning_ratio": 0.0,
                "exhausted": True, "max_preemptions": 1,
                "max_transitions": 9,
                "invariants": ["exactly_once_claims"],
                "violations": [{"invariant": "exactly_once_claims",
                                "schedule_id": "toy_broken:3F",
                                "message": "step 0 applied 2 times"}],
                "sample_fingerprints": {}},
            "needs_jax": {"skipped": "jax"},
        },
    }
    p = tmp_path / "check.json"
    p.write_text(json.dumps(check))
    rep = report.summarize_schedules(str(p))
    assert rep["totals"] == {"schedules": 110, "pruned": 50,
                             "violations": 1, "skipped": 1}
    text = report.render_schedules(rep)
    assert "replay_dup_storm" in text and "exhausted" in text
    assert "budget-capped" in text
    assert "skipped (requires jax)" in text
    assert "--schedule toy_broken:3F" in text
    # CLI: --schedules alone is a valid invocation (no trace positional)
    assert report.main(["--schedules", str(p)]) == 0


def test_trace_report_crash_subsection(tmp_path):
    """slt-crash entries (``"crash": true``) get their own subsection —
    bases, crash points, pruning ratio — and crash violations render
    with the full replayable ``@crash:`` id. Reports from the crash-off
    checker (no crash keys anywhere) must render exactly as before."""
    report = _load_trace_report()
    check = {
        "total_schedules": 190,
        "crash": True,
        "scenarios": {
            "replay_dup_storm": {
                "schedules": 20, "pruned": 4, "pruning_ratio": 1 / 6,
                "exhausted": True, "max_preemptions": 2,
                "max_transitions": 40, "invariants": ["no_errors"],
                "violations": [], "sample_fingerprints": {}},
            "crash_replay_dup_storm": {
                "crash": True, "bases": 12, "crash_schedules": 168,
                "schedules": 170, "pruned": 56,
                "pruning_ratio": 56 / 226, "exhausted": True,
                "max_preemptions": 2, "max_transitions": 64,
                "invariants": ["durable_exactly_once"],
                "violations": [{
                    "invariant": "durable_exactly_once",
                    "schedule_id": "crash_replay_dup_storm:3F@crash:7",
                    "message": "step (0, 'split_step', 1) lost"}],
                "sample_fingerprints": {}},
            "crash_needs_jax": {"skipped": "jax", "crash": True},
        },
    }
    p = tmp_path / "crash-check.json"
    p.write_text(json.dumps(check))
    rep = report.summarize_schedules(str(p))
    assert rep["totals"] == {"schedules": 190, "pruned": 60,
                             "violations": 1, "skipped": 1}
    assert rep["scenarios"]["crash_replay_dup_storm"]["bases"] == 12
    assert "crash" not in rep["scenarios"]["replay_dup_storm"]
    text = report.render_schedules(rep)
    assert "crash-restart schedules" in text
    assert "--schedule crash_replay_dup_storm:3F@crash:7" in text
    assert report.main(["--schedules", str(p)]) == 0
    # tolerant fallback: a crash-off report renders with NO subsection
    old = {"total_schedules": 5, "scenarios": {
        "replay_dup_storm": {"schedules": 5, "pruned": 0}}}
    p2 = tmp_path / "old.json"
    p2.write_text(json.dumps(old))
    text2 = report.render_schedules(report.summarize_schedules(str(p2)))
    assert "crash-restart schedules" not in text2
    assert report.main(["--schedules", str(p2)]) == 0


# --------------------------------------------------------------------- #
# runtime.metrics() snapshot (the in-process twin of GET /metrics)


def test_runtime_metrics_snapshot_shape():
    cfg = Config(mode="split", batch_size=8)
    plan = get_plan(mode="split")
    x, y = _data()
    server = ServerRuntime(plan, cfg, jax.random.PRNGKey(0), x)
    client = SplitClientTrainer(plan, cfg, jax.random.PRNGKey(0),
                                LocalTransport(server))
    tr = obs.enable()
    try:
        for i in range(2):
            client.train_step(x, y, i)
    finally:
        obs.disable()
    snap = server.metrics()
    assert set(snap) == {"histograms", "counters", "gauges",
                         "phase_fractions"}
    assert snap["histograms"]["queue_wait"]["count"] == 2
    assert snap["histograms"]["dispatch"]["count"] == 2
    assert snap["counters"]["split_steps_total"] == 2
    assert snap["gauges"]["acked_step"] == 1.0  # last acked step
    fr = snap["phase_fractions"]
    assert pytest.approx(sum(fr.values()), abs=1e-6) == 1.0
    # the same snapshot renders (the /metrics body) without error
    assert "slt_dispatch_seconds_count 2" in render_prometheus(snap)
