"""Compressed hop wires on the K-stage chain (PR 18).

Pins, in order: compression OFF leaves the chain bit-for-bit on the
legacy wire (both the untouched passthrough and the dense fp32 wire
emulation); a topk8 chain at moderate density stays within a loose
absolute-nats budget of the dense twin while the per-hop byte
accounting shows up in transport stats, stage gauges and the runner's
stage report; Clapping mode is the SAME arithmetic as topk8 (identical
loss series) differing only in persistence (no wire_ef in extras); a
chaos-corrupted compressed hop reply over a REAL HTTP chain surfaces
as the typed retry path — CRC gate or codec validation, never a
silently wrong gradient — and the replayed retry keeps the run
bit-identical to its clean twin; and the adaptive density controller
is a pure function of its note schedule: same feed → same trajectory,
end to end through two identically-seeded chain runs.
"""

import jax
import numpy as np
import pytest

from split_learning_tpu.models import get_plan
from split_learning_tpu.runtime.pipeline_runner import PipelineRunner
from split_learning_tpu.runtime.stage import StageRuntime
from split_learning_tpu.transport import codec
from split_learning_tpu.transport.chaos import ChaosPolicy
from split_learning_tpu.transport.density import (
    DENSITY_LADDER, DensityController)
from split_learning_tpu.transport.http import HttpTransport, SplitHTTPServer
from split_learning_tpu.transport.local import LocalTransport
from split_learning_tpu.utils import Config

BATCH = 8
SEED = 2


def _cfg(microbatches, batch=BATCH):
    return Config(mode="split", model="split_cnn_chain3",
                  batch_size=batch, num_stages=3,
                  microbatches=microbatches, seed=SEED)


def _chain(microbatches, apply_lag, batch=BATCH, compress=None,
           density=0.25, ef_mode="topk8", density_controller=None,
           wire_ids=False):
    """One 3-stage chain over LocalTransport with optional wire
    compression — the launch path's local-chain construction."""
    cfg = _cfg(microbatches, batch)
    plan = get_plan(model="split_cnn_chain3", mode="split")
    sample = np.zeros((batch, 28, 28, 1), np.float32)
    stages = [StageRuntime(plan, i, cfg, jax.random.PRNGKey(SEED),
                           sample, microbatches=microbatches,
                           apply_lag=apply_lag, ef_mode=ef_mode)
              for i in (1, 2)]
    transports = [
        LocalTransport(s, compress=compress, density=density,
                       ef_mode=ef_mode,
                       density_controller=density_controller,
                       wire_id=(f"hop{i + 1}" if wire_ids else None))
        for i, s in enumerate(stages)]
    runner = PipelineRunner(plan, cfg, jax.random.PRNGKey(SEED), sample,
                            transports, microbatches=microbatches)
    runner.density_controller = density_controller
    return runner, stages, transports


def _close(runner, stages):
    runner.close()
    for s in stages:
        s.close()


def _batch(i, batch=BATCH):
    rs = np.random.RandomState(100 + i)
    return (rs.rand(batch, 28, 28, 1).astype(np.float32),
            rs.randint(0, 10, batch).astype(np.int64))


def _big_batches(n=4, batch=32):
    # batch 32: trajectory comparisons on an oscillating tiny-batch
    # series would measure noise, not the codec (test_mpmd_pipeline's
    # convention)
    rs = np.random.RandomState(0)
    return [(rs.rand(batch, 28, 28, 1).astype(np.float32),
             rs.randint(0, 10, batch).astype(np.int64))
            for _ in range(n)]


def _run(runner, steps, batches):
    return [runner.step(*batches[i % len(batches)], i)
            for i in range(steps)]


# ---------------------------------------------------------------------- #
# compression off: the legacy wire, bit for bit
# ---------------------------------------------------------------------- #

def test_compress_off_is_bitwise_legacy():
    """compress=None (untouched passthrough) and compress="none" (the
    dense fp32 wire emulation — encode → decode, no sparsify) both
    produce the identical loss series: turning the feature off leaves
    the PR-16 chain wire exactly as it was."""
    steps, M = 4, 2
    series = {}
    for mode in (None, "none"):
        runner, stages, _ = _chain(M, 1, compress=mode)
        try:
            series[mode] = _run(runner, steps, [_batch(i)
                                                for i in range(4)])
        finally:
            _close(runner, stages)
    assert series[None] == series["none"]


# ---------------------------------------------------------------------- #
# topk8 parity + the per-hop byte accounting surface
# ---------------------------------------------------------------------- #

def test_topk8_chain_parity_and_accounting():
    """A topk8 chain at density 0.3 converges with the dense twin
    (loose absolute-nats budget — the bench leg owns the tight gate)
    and every accounting surface lights up: the transports' raw/wire
    compression counters, each stage's wire_compression_ratio gauge,
    and the runner's per-stage report rows."""
    steps, M = 12, 4
    batches = _big_batches()
    runner_d, stages_d, _ = _chain(M, 1, batch=32, compress=None)
    try:
        dense = _run(runner_d, steps, batches)
    finally:
        _close(runner_d, stages_d)
    runner_c, stages_c, ts = _chain(M, 1, batch=32, compress="topk8",
                                    density=0.3)
    try:
        comp = _run(runner_c, steps, batches)
        gap = abs(float(np.mean(comp[-4:])) - float(np.mean(dense[-4:])))
        assert gap <= 0.6, (gap, comp, dense)
        for t in ts:
            summ = t.stats.summary()
            assert summ["compress_raw_bytes"] > summ["compress_wire_bytes"] > 0
            assert summ["compression_ratio"] > 3.0
        for s in stages_c:
            snap = s.metrics()
            assert snap["gauges"]["wire_compression_ratio"] > 3.0
        rows = runner_c.stage_report()
        for row in rows:
            assert row["compression_ratio"] > 3.0
            assert row["compress_wire_bytes"] > 0
    finally:
        _close(runner_c, stages_c)


def test_clapping_is_topk8_arithmetic_without_the_ledger():
    """Clapping (arXiv:2509.19029 storage-free EF) changes persistence,
    not math: the in-run loss series is BIT-identical to topk8's, but a
    clapping stage's extras sidecar carries no wire_ef entry at all
    (nothing to migrate on a PR-15 handoff) while topk8's does."""
    steps, M = 4, 2
    out = {}
    for mode in ("topk8", "clapping"):
        runner, stages, _ = _chain(M, 1, compress=mode, ef_mode=mode)
        try:
            losses = _run(runner, steps, [_batch(i) for i in range(4)])
            extras = [s.export_runtime_extras(steps) for s in stages]
        finally:
            _close(runner, stages)
        out[mode] = (losses, extras)
    assert out["topk8"][0] == out["clapping"][0]
    assert all("wire_ef" in e for e in out["topk8"][1])
    assert all("wire_ef" not in e for e in out["clapping"][1])


# ---------------------------------------------------------------------- #
# chaos corrupt on a compressed hop: typed refusal, never a wrong grad
# ---------------------------------------------------------------------- #

def test_chaos_corrupt_on_compressed_http_chain_is_exactly_once():
    """Server-side ``corrupt`` faults on a REAL compressed HTTP chain:
    the CRC-sabotaged replies are refused by the client's checksum gate
    (typed TransportError, the retry path), the bounded hop retry
    re-collects the ORIGINAL frame from the replay cache, and the loss
    series is bit-identical to the fault-free twin — at no point does a
    corrupted compressed payload decode into a silently wrong
    gradient."""
    steps, M, density = 4, 2, 0.25

    def http_chain(policy):
        cfg = _cfg(M)
        plan = get_plan(model="split_cnn_chain3", mode="split")
        sample = np.zeros((BATCH, 28, 28, 1), np.float32)
        stages = [StageRuntime(plan, i, cfg, jax.random.PRNGKey(SEED),
                               sample, microbatches=M, apply_lag=1,
                               ef_mode="topk8")
                  for i in (1, 2)]
        servers = [SplitHTTPServer(s, compress="topk8", density=density,
                                   chaos=policy).start()
                   for s in stages]
        ts = [HttpTransport(srv.url, compress="topk8", density=density)
              for srv in servers]
        runner = PipelineRunner(plan, cfg, jax.random.PRNGKey(SEED),
                                sample, ts, microbatches=M)
        return runner, stages, servers

    runner_c, stages_c, servers_c = http_chain(None)
    try:
        clean = _run(runner_c, steps, [_batch(i) for i in range(4)])
    finally:
        _close(runner_c, stages_c)
        for srv in servers_c:
            srv.stop()

    policy = ChaosPolicy("corrupt=0.5", seed=3)
    runner_x, stages_x, servers_x = http_chain(policy)
    try:
        chaotic = _run(runner_x, steps, [_batch(i) for i in range(4)])
        assert chaotic == clean
        assert policy.injected.get("corrupt", 0) > 0
        # the refused frames were re-served from the replay cache as
        # the ORIGINAL bytes — the server never re-applied, the client
        # never re-packed into a drifted EF ledger
        assert sum(s.counters()["replay_body_hits"]
                   for s in stages_x) > 0
        for s in stages_x:
            ctr = s.counters()
            ops = (("hop_fwd", "hop_bwd") if not s.is_last
                   else ("hop_loss",))
            for op in ops:
                assert ctr[op] == steps * M, (s.party, op, ctr)
    finally:
        _close(runner_x, stages_x)
        for srv in servers_x:
            srv.stop()


def test_corrupt_compressed_payload_is_typed_codec_error():
    """A packed topk8 frame that passes transport framing but fails
    codec validation (truncated bitmap, out-of-range index, bad count)
    raises the typed CodecError — the one exception class the HTTP
    client maps to the TransportError retry path — rather than
    decoding into a wrong-shaped or wrong-valued tensor."""
    rs = np.random.RandomState(0)
    packed, _ = codec.topk8_compress(
        rs.randn(64, 64).astype(np.float32), 0.1)
    bad_count = dict(packed, n=-1)
    with pytest.raises(codec.CodecError):
        codec.topk8_decompress(bad_count)
    if "idx" in packed:
        sab = dict(packed, idx=np.array([10 ** 6], np.int32))
    else:
        sab = dict(packed, m=packed["m"][:1])
    with pytest.raises(codec.CodecError):
        codec.topk8_decompress(sab)
    # and through the tree walker the caller actually uses
    with pytest.raises(codec.CodecError):
        codec.decompress_tree({"grads": sab})


# ---------------------------------------------------------------------- #
# the adaptive density controller: deterministic by construction
# ---------------------------------------------------------------------- #

def test_density_controller_validation_and_ladder():
    with pytest.raises(ValueError):
        DensityController(window=0)
    with pytest.raises(ValueError):
        DensityController(ladder=(0.1, 0.2))  # not decreasing
    with pytest.raises(ValueError):
        DensityController(start_rung=99)
    dc = DensityController()
    assert dc.density("hop1") == DENSITY_LADDER[2] == 0.1


def test_density_controller_decision_rule():
    """First window is baseline only; a drift above budget loosens
    every wire one rung; slack tightens exactly the least-compressing
    wire."""
    dc = DensityController(window=2, budget_nats=0.05)
    for wire in ("hop1", "hop2"):
        dc.density(wire)
    # window 1: baseline at mean 1.0
    dc.note_ratio("hop1", 1000, 100)   # 10x
    dc.note_ratio("hop2", 1000, 250)   # 4x — the worst compressor
    dc.note_loss(1.0)
    dc.note_loss(1.0)
    assert dc.densities() == {"hop1": 0.1, "hop2": 0.1}
    # window 2: flat loss => tighten hop2 (lowest achieved ratio)
    dc.note_ratio("hop1", 1000, 100)
    dc.note_ratio("hop2", 1000, 250)
    dc.note_loss(1.0)
    dc.note_loss(1.0)
    assert dc.densities() == {"hop1": 0.1, "hop2": 0.05}
    # window 3: loss blows the budget => every wire loosens one rung
    dc.note_loss(2.0)
    dc.note_loss(2.0)
    assert dc.densities() == {"hop1": 0.2, "hop2": 0.1}
    snap = dc.snapshot()
    assert [r["action"] for r in snap["trajectory"]] == [
        "baseline", "tighten", "loosen"]
    assert snap["windows_closed"] == 3


def test_density_controller_pure_function_of_feed():
    """Identical note schedules → identical snapshots, including the
    full decision trajectory (no clock, no RNG, no arrival order)."""
    def feed(dc):
        for i in range(20):
            dc.note_ratio("hop1", 4096, 256 + 16 * (i % 3))
            dc.note_ratio("hop2", 4096, 512)
            dc.note_loss(2.0 - 0.01 * i + (0.3 if i == 13 else 0.0))
        return dc.snapshot()

    a = feed(DensityController(window=4, budget_nats=0.05))
    b = feed(DensityController(window=4, budget_nats=0.05))
    assert a == b
    assert a["windows_closed"] == 5
    assert len(a["trajectory"]) == 5


def test_density_auto_chain_run_is_deterministic():
    """End to end: two identically-seeded compressed chain runs, each
    with its own fresh controller, land on the identical controller
    snapshot AND the identical loss series — the acceptance criterion
    for ``--compress-density auto``. The runner also surfaces the
    snapshot in trace metadata and the per-wire density in its stage
    report."""
    steps, M = 6, 2

    def auto_run():
        dc = DensityController(window=2)
        runner, stages, _ = _chain(M, 1, compress="topk8",
                                   density_controller=dc, wire_ids=True)
        try:
            losses = _run(runner, steps, [_batch(i) for i in range(4)])
            meta = runner.trace_metadata()
            rows = runner.stage_report()
        finally:
            _close(runner, stages)
        return losses, dc.snapshot(), meta, rows

    losses_a, snap_a, meta_a, rows_a = auto_run()
    losses_b, snap_b, _, _ = auto_run()
    assert losses_a == losses_b
    assert snap_a == snap_b
    assert snap_a["windows_closed"] == steps // 2
    assert sorted(snap_a["densities"]) == ["hop1", "hop2"]
    assert meta_a["density"] == snap_a
    for row, wire in zip(rows_a, ("hop1", "hop2")):
        assert row["density"] == snap_a["densities"][wire]
