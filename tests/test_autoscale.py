"""Elastic autoscaling control plane (runtime/autoscale.py): the
policy's deterministic verdicts (band/reject/p99 pressure, burn as
mid-band tiebreak, hysteresis, per-direction cooldowns under an
injectable clock), window->signal reduction, and the Autoscaler's scale
events against a live ReplicaGroup — scale-up adopts with minimal HRW
churn and replay-clean reroutes, scale-down retires through the
exactly-once handoff, close() drains an in-flight handoff, and the
whole module is zero-overhead when off. Protocol legs use the
test_replica.py jax-light stub around a real ReplayCache."""

import threading

import pytest

from split_learning_tpu.obs import spans
from split_learning_tpu.runtime import (
    ReplicaGroup, maybe_replicate, rendezvous_pick)
from split_learning_tpu.runtime import autoscale as rt_autoscale
from split_learning_tpu.runtime.autoscale import (
    Autoscaler, AutoscalePolicy, AutoscaleSignals, signals_from_window)
from split_learning_tpu.runtime.breaker import OPEN
from split_learning_tpu.runtime.replay import ReplayCache


class _Clock:
    """Injectable monotonic clock: the policy's cooldowns become pure
    functions of the test's explicit time steps."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _StubReplica:
    """test_replica.py's claim-lifecycle stub: a real ReplayCache
    decides ownership, only the owner applies, and the reply pins which
    payload materialized it."""

    def __init__(self, idx):
        self.idx = idx
        self.replay = ReplayCache(window=16)
        self.applies = []

    def health(self):
        return {"step": len(self.applies), "status": "serving"}

    def split_step(self, payload, labels, step, client_id=0):
        entry, owner = self.replay.begin(client_id, "split_step", step)
        if not owner:
            return self.replay.wait(entry, timeout=30.0)
        self.applies.append((client_id, step, payload))
        value = ("reply", client_id, step, self.idx, payload)
        self.replay.resolve(entry, value)
        return value

    def flush_deferred(self):
        return 0

    def metrics(self):
        return {"counters": {}, "gauges": {}, "histograms": {}}

    def export_runtime_extras(self, step):
        from split_learning_tpu.runtime.checkpoint import build_extras
        return build_extras(step, 1, replay=self.replay.export_state(),
                            wire_ef=[])

    def close(self):
        pass


def _policy(clock=None, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("hysteresis_up", 1)
    kw.setdefault("hysteresis_down", 1)
    kw.setdefault("cooldown_up_s", 0.0)
    kw.setdefault("cooldown_down_s", 0.0)
    return AutoscalePolicy(clock=clock or _Clock(), **kw)


# --------------------------------------------------------------------- #
# policy verdicts
# --------------------------------------------------------------------- #

def test_policy_pressure_signals_scale_up():
    """Each pressure signal alone breaches its ceiling -> up; a missing
    signal never triggers."""
    for sig in (AutoscaleSignals(occupancy=0.95),
                AutoscaleSignals(reject_rate=0.5),
                AutoscaleSignals(occupancy=0.5, p99_over_slo=1.4)):
        d = _policy().decide(sig, n_live=1)
        assert d.direction == "up", sig
    # all-None window: no evidence of pressure, idle argues down
    d = _policy().decide(AutoscaleSignals(), n_live=2)
    assert d.direction == "down"
    assert "idle" in d.reason


def test_policy_scale_down_requires_every_signal_comfortable():
    """Idle occupancy alone is not enough: a reject or an over-SLO p99
    in the same window vetoes the down."""
    p = _policy()
    assert p.decide(AutoscaleSignals(occupancy=0.1),
                    n_live=2).direction == "down"
    assert _policy().decide(
        AutoscaleSignals(occupancy=0.1, reject_rate=0.005),
        n_live=2).direction == "hold"
    assert _policy().decide(
        AutoscaleSignals(occupancy=0.1, p99_over_slo=1.2),
        n_live=2).direction == "up"


def test_policy_burn_is_midband_tiebreak_only():
    """The burn gauge integrates history: it must break a mid-band tie
    toward up, but a stale burn must NOT block (or outvote) a
    scale-down once the window itself is idle — the regression that
    pinned every down to after the run ended."""
    # mid-band occupancy + burning -> up (the tiebreak)
    d = _policy().decide(AutoscaleSignals(occupancy=0.5, burn=2.0),
                         n_live=2)
    assert d.direction == "up" and "burn" in d.reason
    # idle window + stale burn -> down anyway
    d = _policy().decide(AutoscaleSignals(occupancy=0.1, burn=2.0),
                         n_live=2)
    assert d.direction == "down"
    # mid-band, no burn -> hold
    assert _policy().decide(AutoscaleSignals(occupancy=0.5),
                            n_live=2).direction == "hold"


def test_policy_hysteresis_counts_consecutive_windows():
    p = _policy(hysteresis_up=2, hysteresis_down=2)
    up = AutoscaleSignals(occupancy=0.95)
    idle = AutoscaleSignals(occupancy=0.05)
    assert p.decide(up, 1).direction == "hold"       # 1/2
    assert p.decide(idle, 2).direction == "hold"     # streak broken: 1/2
    assert p.decide(up, 1).direction == "hold"       # 1/2 again
    assert p.decide(up, 1).direction == "up"         # 2/2


def test_policy_cooldowns_per_direction_injectable_clock():
    clk = _Clock()
    p = _policy(clock=clk, cooldown_up_s=5.0, cooldown_down_s=10.0)
    up = AutoscaleSignals(occupancy=0.95)
    idle = AutoscaleSignals(occupancy=0.05)
    assert p.decide(up, 1).direction == "up"
    clk.t = 2.0
    assert p.decide(up, 2).reason == "cooldown_up"
    # the down direction has its own clock — an up does not charge it
    assert p.decide(idle, 2).direction == "down"
    clk.t = 4.0
    assert p.decide(idle, 2).reason == "cooldown_down"
    clk.t = 7.0                                      # up cooled, down not
    assert p.decide(up, 1).direction == "up"
    clk.t = 13.0
    assert p.decide(idle, 2).direction == "down"


def test_policy_floor_and_ceiling():
    p = _policy(min_replicas=1, max_replicas=2)
    d = p.decide(AutoscaleSignals(occupancy=0.95), n_live=2)
    assert d.direction == "hold" and "at_max" in d.reason
    d = _policy().decide(AutoscaleSignals(occupancy=0.05), n_live=1)
    assert d.direction == "hold" and "at_min" in d.reason


def test_policy_deterministic_replay():
    """Same window sequence, same clock steps -> identical verdicts
    (SLT004's determinism scope extends to the control plane)."""
    windows = [AutoscaleSignals(occupancy=o, reject_rate=r)
               for o, r in ((0.9, 0.0), (0.95, 0.2), (0.5, 0.0),
                            (0.1, 0.0), (0.05, 0.0), (0.9, 0.0))]

    def run():
        clk = _Clock()
        p = _policy(clock=clk, cooldown_up_s=1.0, cooldown_down_s=1.0,
                    hysteresis_down=2)
        out = []
        for i, w in enumerate(windows):
            clk.t = float(i)
            d = p.decide(w, 2)
            out.append((d.direction, d.reason))
        return out

    assert run() == run()


def test_policy_validates_config():
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=0)
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        AutoscalePolicy(band=(0.8, 0.2))


# --------------------------------------------------------------------- #
# window -> signals
# --------------------------------------------------------------------- #

def test_signals_from_window_arithmetic():
    window = {
        "index": 7,
        "counters": {"coalesce_groups_flushed": 4.0,
                     "coalesce_requests_coalesced": 12.0,
                     spans.ADMISSION_ADMITTED: 90.0,
                     spans.ADMISSION_REJECTED: 10.0},
        "gauges": {f"{spans.SLO_BURN_FAST}:p99": 1.5,
                   f"{spans.SLO_BURN_FAST}:err": 0.5},
        "percentiles": {spans.DISPATCH: {"p99": 80.0}},
    }
    s = signals_from_window(window, coalesce_max=4, slo_ms=40.0)
    assert s.occupancy == pytest.approx((12.0 / 4.0) / 4)
    assert s.reject_rate == pytest.approx(0.1)
    assert s.burn == pytest.approx(1.5)           # max across burn gauges
    assert s.p99_over_slo == pytest.approx(2.0)
    assert s.window_index == 7


def test_signals_missing_evidence_is_none():
    """No traffic, no SLO -> every signal None (and the policy treats
    None as 'no evidence', never as pressure)."""
    s = signals_from_window({"index": 0, "counters": {}, "gauges": {},
                             "percentiles": {}}, coalesce_max=4)
    assert (s.occupancy, s.reject_rate, s.burn, s.p99_over_slo) == \
        (None, None, None, None)
    # an SLO without a p99 sample stays None too
    s = signals_from_window({"counters": {}, "gauges": {},
                             "percentiles": {}}, slo_ms=40.0)
    assert s.p99_over_slo is None


# --------------------------------------------------------------------- #
# capacity + scale events against a live group
# --------------------------------------------------------------------- #

class _StubRing:
    """A TelemetryRing stand-in the test scripts window by window."""

    def __init__(self):
        self.queue = []
        self.interval_s = 0.1

    def advance(self):
        pass

    def push(self, **signals):
        idx = len(self.queue)
        counters = {}
        if "occupancy" in signals:
            counters = {"coalesce_groups_flushed": 1.0,
                        "coalesce_requests_coalesced":
                            signals["occupancy"] * 4}
        self.queue.append({"index": idx, "counters": counters,
                           "gauges": {}, "percentiles": {}})

    def windows(self, last=1):
        return self.queue[-last:] if self.queue else []


def _autoscaler(group, ring, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    policy = _policy(**kw)
    return Autoscaler(group, lambda idx: _StubReplica(idx), policy,
                      ring, coalesce_max=4)


def test_capacity_excludes_breaker_open_replica():
    group = ReplicaGroup([_StubReplica(i) for i in range(3)])
    assert group.capacity_replicas() == [0, 1, 2]
    group._slots[1].breaker.state = OPEN
    assert group.capacity_replicas() == [0, 2]
    assert group.live_replicas() == [0, 1, 2]     # open != dead


def test_autoscaler_scales_up_and_down_on_window_signals():
    group = ReplicaGroup([_StubReplica(0)])
    ring = _StubRing()
    a = _autoscaler(group, ring)

    assert a.maybe_scale() is None                 # no window yet
    ring.push(occupancy=0.95)
    d = a.maybe_scale()
    assert d.direction == "up" and d.executed
    assert sorted(group.live_replicas()) == [0, 1]
    assert a.maybe_scale() is None                 # same window: no verdict
    ring.push(occupancy=0.05)
    d = a.maybe_scale()
    assert d.direction == "down" and d.executed
    assert len(group.live_replicas()) == 1
    assert a.scale_ups == 1 and a.scale_downs == 1
    assert [e["direction"] for e in a.events] == ["up", "down"]
    assert all(e["t_s"] >= 0 for e in a.events)
    # the dashboard gauge carries the last verdict (-1 = down)
    assert group.metrics()["gauges"][spans.AUTOSCALE_DECISION] == -1.0
    counters = group.counters()
    assert counters["replica_scale_ups"] == 1
    assert counters["replica_scale_downs"] == 1
    group.close()


def test_autoscaler_down_blocked_while_handoff_in_flight():
    group = ReplicaGroup([_StubReplica(i) for i in range(2)])
    ring = _StubRing()
    a = _autoscaler(group, ring)
    group.handoff_in_flight = lambda: True
    ring.push(occupancy=0.05)
    d = a.maybe_scale()
    assert d.direction == "down" and not d.executed
    assert "handoff in flight" in d.reason
    assert len(group.live_replicas()) == 2
    group.close()


def test_autoscaler_retires_least_loaded_replica():
    group = ReplicaGroup([_StubReplica(i) for i in range(2)])
    # load both caches through the router so route_counts sees skew
    heavy = group.assignment(0)
    for c in range(24):
        group.split_step(f"p{c}", None, 1, c)
    counts = group.route_counts()
    light = min(counts, key=lambda idx: (counts[idx], -idx))
    ring = _StubRing()
    a = _autoscaler(group, ring)
    ring.push(occupancy=0.05)
    d = a.maybe_scale()
    assert d.executed and d.replica == light
    assert group.live_replicas() == [1 - light]
    del heavy
    group.close()


# --------------------------------------------------------------------- #
# scale-up adoption: minimal churn, replay-clean reroutes
# --------------------------------------------------------------------- #

def test_add_replica_minimal_churn_and_only_to_newcomer():
    """HRW N->N+1: moved clients land ONLY on the newcomer, and the
    moved fraction stays near 1/(N+1) (<= 1.5x the ideal share)."""
    n, clients = 3, 400
    group = ReplicaGroup([_StubReplica(i) for i in range(n)])
    before = {c: group.assignment(c) for c in range(clients)}
    new_idx = group.add_replica(lambda idx: _StubReplica(idx))
    assert new_idx == n
    moved = 0
    for c in range(clients):
        after = group.assignment(c)
        if after != before[c]:
            assert after == new_idx, f"client {c} moved to a bystander"
            moved += 1
    assert 0 < moved <= 1.5 * clients / (n + 1)
    group.close()


def test_scale_up_rerouted_garbage_dup_replays_clean():
    """A step applied before the scale-up, retransmitted after it with a
    garbage payload by a client HRW moved to the newcomer: served the
    migrated original reply bit-identically, applied exactly once."""
    group = ReplicaGroup([_StubReplica(i) for i in range(2)])
    # find a client the 2->3 transition will move
    mover = next(c for c in range(512)
                 if rendezvous_pick(c, [0, 1, 2]) == 2)
    origin = group.assignment(mover)
    orig = group.split_step("orig-payload", None, 5, mover)
    group.add_replica(lambda idx: _StubReplica(idx))
    assert group.assignment(mover) == 2

    dup = group.split_step("garbage-payload", None, 5, mover)
    assert dup == orig
    assert dup[-1] == "orig-payload"
    assert dup[3] == origin                       # the original applier
    applies = [a for r in group.replicas for a in r.applies
               if a[0] == mover and a[1] == 5]
    assert len(applies) == 1
    assert group.replicas[2].applies == []        # newcomer applied nothing
    group.close()


def test_scale_down_garbage_dup_served_bit_identical_once():
    """The acceptance pin: a step applied on the scale-down victim,
    retransmitted with a garbage payload after the policy retired it —
    one apply total, the dup answered from the merged replay entry."""
    group = ReplicaGroup([_StubReplica(i) for i in range(2)])
    victim = group.assignment(7)
    orig = group.split_step("orig-payload", None, 2, 7)
    assert group.replicas[victim].applies[-1][2] == "orig-payload"

    ring = _StubRing()
    a = _autoscaler(group, ring)
    # idle window, but the applier must be the one the policy retires:
    # load the other replica with more clients so least-loaded picks
    # the victim deterministically
    survivor = 1 - victim
    others = [c for c in range(8, 256)
              if group.assignment(c) == survivor][:2]
    for i, c in enumerate(others):
        group.split_step(f"other{i}", None, 1, c)
    ring.push(occupancy=0.01)
    d = a.maybe_scale()
    assert d.executed and d.direction == "down" and d.replica == victim

    dup = group.split_step("garbage-payload", None, 2, 7)
    assert dup == orig
    assert dup[-1] == "orig-payload"
    applies = [x for r in group.replicas for x in r.applies
               if x[0] == 7 and x[1] == 2]
    assert len(applies) == 1
    assert group.counters()["replica_scale_downs"] == 1
    group.close()


# --------------------------------------------------------------------- #
# close() vs in-flight handoff (satellite: drain, don't drop)
# --------------------------------------------------------------------- #

def test_group_close_drains_inflight_handoff():
    """close() racing a scale-down handoff waits for the commit instead
    of closing the survivors out from under the merge: the migrated
    entry still serves the dup after close began."""
    group = ReplicaGroup([_StubReplica(i) for i in range(2)])
    victim = group.assignment(0)
    orig = group.split_step("orig", None, 1, 0)

    release = threading.Event()
    real_extras = group.replicas[victim].export_runtime_extras

    def slow_extras(step):
        release.wait(timeout=30.0)
        return real_extras(step)

    group.replicas[victim].export_runtime_extras = slow_extras
    closer_done = threading.Event()

    def closer():
        # wait until the handoff is fenced, then race close against it
        while not group.handoff_in_flight():
            pass
        group.close()
        closer_done.set()

    remover = threading.Thread(
        target=group.remove_replica, args=(victim,))
    t = threading.Thread(target=closer)
    remover.start()
    t.start()
    assert not closer_done.wait(timeout=0.3)      # close() is draining
    release.set()
    remover.join(timeout=30.0)
    t.join(timeout=30.0)
    assert closer_done.is_set()
    # the merge landed before the survivors closed: dup served from it
    dup = group.split_step("garbage", None, 1, 0)
    assert dup == orig
    assert group.counters()["replica_handoffs"] == 1


# --------------------------------------------------------------------- #
# config plumbing + zero-overhead-off
# --------------------------------------------------------------------- #

def test_env_config_parsing(monkeypatch):
    for var in ("SLT_AUTOSCALE", "SLT_AUTOSCALE_MIN", "SLT_AUTOSCALE_MAX",
                "SLT_AUTOSCALE_COOLDOWN_S"):
        monkeypatch.delenv(var, raising=False)
    cfg = rt_autoscale.env_config()
    assert cfg["enabled"] is False
    assert cfg["min_replicas"] == 1 and cfg["max_replicas"] == 4
    monkeypatch.setenv("SLT_AUTOSCALE", "1")
    monkeypatch.setenv("SLT_AUTOSCALE_MIN", "2")
    monkeypatch.setenv("SLT_AUTOSCALE_MAX", "6")
    monkeypatch.setenv("SLT_AUTOSCALE_COOLDOWN_S", "0.5")
    cfg = rt_autoscale.env_config()
    assert cfg == {"enabled": True, "min_replicas": 2,
                   "max_replicas": 6, "cooldown_s": 0.5}


def test_args_config_cli_over_env(monkeypatch):
    import argparse
    for var in ("SLT_AUTOSCALE", "SLT_AUTOSCALE_MIN", "SLT_AUTOSCALE_MAX",
                "SLT_AUTOSCALE_COOLDOWN_S"):
        monkeypatch.delenv(var, raising=False)
    ns = argparse.Namespace(autoscale=False, autoscale_min=None,
                            autoscale_max=None, autoscale_cooldown_s=None)
    # off everywhere -> None: the zero-overhead pin, no policy object
    assert rt_autoscale.args_config(ns) is None
    # a namespace without the attrs at all (stage role) is off too
    assert rt_autoscale.args_config(argparse.Namespace()) is None
    # env on, CLI overrides the numbers
    monkeypatch.setenv("SLT_AUTOSCALE", "true")
    ns.autoscale_max = 8
    cfg = rt_autoscale.args_config(ns)
    assert cfg["enabled"] is True and cfg["max_replicas"] == 8
    # CLI flag alone turns it on
    monkeypatch.delenv("SLT_AUTOSCALE")
    ns.autoscale = True
    ns.autoscale_min = 2
    cfg = rt_autoscale.args_config(ns)
    assert cfg["enabled"] is True and cfg["min_replicas"] == 2


def test_policy_from_config_maps_cooldowns():
    clk = _Clock()
    p = rt_autoscale.policy_from_config(
        {"enabled": True, "min_replicas": 2, "max_replicas": 5,
         "cooldown_s": 3.0}, clock=clk)
    assert p.min_replicas == 2 and p.max_replicas == 5
    assert p.cooldown_up_s == 3.0
    assert p.cooldown_down_s == 6.0               # retiring is the slower reflex
    assert p._clock is clk


def test_zero_overhead_off_maybe_replicate_untouched():
    """--replicas 1 without --autoscale stays the bare runtime — no
    group, no router, no policy anywhere near the step path."""
    bare = _StubReplica(0)
    assert maybe_replicate(lambda idx: bare, 1) is bare
